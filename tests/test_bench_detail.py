"""The bench's evidence-banking rules: a CPU run must never clobber TPU data.

r4 lost its working-tree TPU capture to exactly this overwrite (VERDICT r4
weak #2); these tests pin the per-platform write contract of bench.py.
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from bench import _detail_platform, _write_detail


def _read(tmp, name):
    return json.loads((tmp / name).read_text())


def test_detail_platform_classification():
    assert _detail_platform({"solve_tier": {"platform": "tpu"}}) == "tpu"
    assert _detail_platform({"solve_tier": {"platform": "cpu"}}) == "cpu"
    assert _detail_platform({"sqlite_baseline_rate": 1}) == "cpu"
    # any tpu tier anywhere marks the run as hardware evidence
    assert (
        _detail_platform(
            {"solve_tier": {"platform": "cpu"}, "collapsed_tier": {"platform": "tpu"}}
        )
        == "tpu"
    )


def test_cpu_run_with_no_prior_capture_writes_legacy(tmp_path):
    _write_detail({"solve_tier": {"platform": "cpu"}}, here=str(tmp_path))
    assert _detail_platform(_read(tmp_path, "BENCH_DETAIL.json")) == "cpu"
    assert _detail_platform(_read(tmp_path, "BENCH_DETAIL.cpu.json")) == "cpu"


def test_tpu_run_writes_both_and_cpu_fallback_cannot_clobber(tmp_path):
    _write_detail({"solve_tier": {"platform": "tpu", "run": 1}}, here=str(tmp_path))
    assert _detail_platform(_read(tmp_path, "BENCH_DETAIL.json")) == "tpu"
    # A later CPU fallback only touches the cpu sidecar...
    _write_detail({"solve_tier": {"platform": "cpu", "run": 2}}, here=str(tmp_path))
    legacy = _read(tmp_path, "BENCH_DETAIL.json")
    assert _detail_platform(legacy) == "tpu" and legacy["solve_tier"]["run"] == 1
    assert _read(tmp_path, "BENCH_DETAIL.cpu.json")["solve_tier"]["run"] == 2
    # ...and a fresh TPU run updates the hardware record again.
    _write_detail({"solve_tier": {"platform": "tpu", "run": 3}}, here=str(tmp_path))
    assert _read(tmp_path, "BENCH_DETAIL.json")["solve_tier"]["run"] == 3
    assert _read(tmp_path, "BENCH_DETAIL.tpu.json")["solve_tier"]["run"] == 3


def test_corrupt_legacy_file_is_replaced_not_fatal(tmp_path):
    (tmp_path / "BENCH_DETAIL.json").write_text("{not json")
    _write_detail({"solve_tier": {"platform": "cpu"}}, here=str(tmp_path))
    assert _detail_platform(_read(tmp_path, "BENCH_DETAIL.json")) == "cpu"


def test_tpu_run_carries_forward_missing_tiers_with_provenance(tmp_path):
    """A skipped tier (e.g. hier ladder behind its relay-health gate) must
    not erase the banked capture from a healthier window."""
    _write_detail(
        {
            "solve_tier": {"platform": "tpu", "run": 1},
            "baseline_row5_hier": {"ok": True, "run": 1},
        },
        here=str(tmp_path),
    )
    # Next tpu run skipped the hier tier entirely.
    fresh = {"solve_tier": {"platform": "tpu", "run": 2}}
    _write_detail(fresh, here=str(tmp_path))
    for name in ("BENCH_DETAIL.tpu.json", "BENCH_DETAIL.json"):
        banked = _read(tmp_path, name)
        assert banked["solve_tier"]["run"] == 2
        assert banked["baseline_row5_hier"]["run"] == 1
        assert banked["baseline_row5_hier_carried"] == "prior tpu capture"
    # The caller's dict is untouched (later writes re-derive the merge).
    assert "baseline_row5_hier" not in fresh
    # A third run that DID capture the tier sheds both value and marker.
    _write_detail(
        {
            "solve_tier": {"platform": "tpu", "run": 3},
            "baseline_row5_hier": {"ok": True, "run": 3},
        },
        here=str(tmp_path),
    )
    banked = _read(tmp_path, "BENCH_DETAIL.tpu.json")
    assert banked["baseline_row5_hier"]["run"] == 3
    assert "baseline_row5_hier_carried" not in banked


def test_cpu_sidecar_never_receives_carried_tpu_keys(tmp_path):
    _write_detail(
        {
            "solve_tier": {"platform": "tpu", "run": 1},
            "baseline_row5_hier": {"ok": True},
        },
        here=str(tmp_path),
    )
    _write_detail({"solve_tier": {"platform": "cpu", "run": 2}}, here=str(tmp_path))
    cpu = _read(tmp_path, "BENCH_DETAIL.cpu.json")
    assert "baseline_row5_hier" not in cpu and "baseline_row5_hier_carried" not in cpu


def test_none_valued_tier_does_not_clobber_banked_capture(tmp_path):
    """solve_tier = None (every dense child failed) counts as missing."""
    _write_detail(
        {
            "collapsed_tier": {"platform": "tpu", "run": 1},
            "solve_tier": {"platform": "tpu", "run": 1},
        },
        here=str(tmp_path),
    )
    _write_detail(
        {"collapsed_tier": {"platform": "tpu", "run": 2}, "solve_tier": None},
        here=str(tmp_path),
    )
    banked = _read(tmp_path, "BENCH_DETAIL.tpu.json")
    assert banked["collapsed_tier"]["run"] == 2
    assert banked["solve_tier"]["run"] == 1
    assert banked["solve_tier_carried"] == "prior tpu capture"


def test_cpu_fallback_tier_cannot_displace_banked_tpu_tier(tmp_path):
    """Dense TPU children failed; the 131k cpu fallback filled solve_tier —
    the tpu file keeps the hardware capture, fallback under its own key."""
    _write_detail(
        {
            "collapsed_tier": {"platform": "tpu", "run": 1},
            "solve_tier": {"platform": "tpu", "run": 1},
        },
        here=str(tmp_path),
    )
    _write_detail(
        {
            "collapsed_tier": {"platform": "tpu", "run": 2},
            "solve_tier": {"platform": "cpu", "run": 2},
        },
        here=str(tmp_path),
    )
    banked = _read(tmp_path, "BENCH_DETAIL.tpu.json")
    assert banked["solve_tier"] == {"platform": "tpu", "run": 1}
    assert banked["solve_tier_carried"] == "prior tpu capture"
    assert banked["solve_tier_cpu_fallback"] == {"platform": "cpu", "run": 2}


def test_prior_none_value_is_not_carried_as_capture(tmp_path):
    _write_detail(
        {"collapsed_tier": {"platform": "tpu", "run": 1}, "solve_tier": None},
        here=str(tmp_path),
    )
    _write_detail(
        {"collapsed_tier": {"platform": "tpu", "run": 2}, "solve_tier": None},
        here=str(tmp_path),
    )
    banked = _read(tmp_path, "BENCH_DETAIL.tpu.json")
    assert banked["solve_tier"] is None
    assert "solve_tier_carried" not in banked


def test_non_dict_prior_files_are_tolerated(tmp_path):
    (tmp_path / "BENCH_DETAIL.tpu.json").write_text("[1, 2]")
    (tmp_path / "BENCH_DETAIL.json").write_text("\"x\"")
    _write_detail({"solve_tier": {"platform": "tpu", "run": 1}}, here=str(tmp_path))
    assert _read(tmp_path, "BENCH_DETAIL.tpu.json")["solve_tier"]["run"] == 1
    (tmp_path / "BENCH_DETAIL.json").write_text("[]")
    _write_detail({"solve_tier": {"platform": "cpu", "run": 2}}, here=str(tmp_path))
    assert _read(tmp_path, "BENCH_DETAIL.json")["solve_tier"]["run"] == 2


def test_host_stage_keys_never_carry_forward(tmp_path):
    """Prior rpc numbers must not pair with a fresh session's baseline."""
    _write_detail(
        {
            "sqlite_baseline_rate": 100000,
            "collapsed_tier": {"platform": "tpu", "run": 1},
            "rpc_msgs_per_sec": {"asyncio": 20000},
        },
        here=str(tmp_path),
    )
    _write_detail(
        {
            "sqlite_baseline_rate": 40000,
            "collapsed_tier": {"platform": "tpu", "run": 2},
        },
        here=str(tmp_path),
    )
    banked = _read(tmp_path, "BENCH_DETAIL.tpu.json")
    assert banked["sqlite_baseline_rate"] == 40000
    assert "rpc_msgs_per_sec" not in banked
    assert banked["collapsed_tier"]["run"] == 2


def test_carry_falls_back_to_legacy_when_tpu_sidecar_corrupt(tmp_path):
    _write_detail(
        {
            "collapsed_tier": {"platform": "tpu", "run": 1},
            "baseline_row5_hier": {"ok": True, "run": 1},
        },
        here=str(tmp_path),
    )
    (tmp_path / "BENCH_DETAIL.tpu.json").write_text("{trunc")
    _write_detail(
        {"collapsed_tier": {"platform": "tpu", "run": 2}}, here=str(tmp_path)
    )
    for name in ("BENCH_DETAIL.tpu.json", "BENCH_DETAIL.json"):
        banked = _read(tmp_path, name)
        assert banked["collapsed_tier"]["run"] == 2
        assert banked["baseline_row5_hier"]["run"] == 1
