"""Timers & reminders integration tests.

The tentpole subsystem end to end: volatile timers through the dispatch
queue (cancelled at shutdown AND at panic deallocation), durable reminders
delivered by the shard-owning node's ReminderDaemon, failover of shard
ownership on both an abrupt server kill (lease expiry bounds the gap) and a
graceful drain (handoff releases leases immediately), and the missed-tick
catch-up policies — plus deterministic daemon-level unit tests with a stub
delivery client.
"""

import asyncio
import time
from collections import defaultdict

import pytest

from rio_tpu import (
    AdminCommand,
    AppData,
    LocalObjectPlacement,
    LocalReminderStorage,
    Registry,
    ReminderDaemonConfig,
    ReminderFired,
    ReminderStorage,
    ServerInfo,
    ServiceObject,
    handler,
    message,
)
from rio_tpu.cluster.storage import LocalStorage, Member
from rio_tpu.object_placement import ObjectPlacementItem
from rio_tpu.registry import ObjectId
from rio_tpu.reminders import Reminder
from rio_tpu.reminders.daemon import SHARD_TYPE, ReminderDaemon
from rio_tpu.utils import ExponentialBackoff

from .server_utils import Cluster, run_integration_test

# Global tick record: survives re-activation and server moves (everything
# runs in one process), so failover tests can see who delivered what when.
RECORD: dict[str, list[tuple[str, int, float]]] = defaultdict(list)


@message
class StartTimer:
    name: str = "t"
    period: float = 0.05


@message
class StopTimer:
    name: str = "t"


@message
class TimerTick:
    name: str = "t"


@message
class StartReminder:
    name: str = "r"
    period: float = 0.2
    first_in: float = 0.2


@message
class Poke:
    mode: str = "ok"  # ok | panic | shutdown


@message
class Ticks:
    timer_ticks: int = 0
    server: str = ""
    stopped: bool = False


class Waker(ServiceObject):
    def __init__(self):
        self.timer_ticks = 0

    @handler
    async def start_timer(self, msg: StartTimer, ctx: AppData) -> Ticks:
        self.register_timer(ctx, msg.name, msg.period, TimerTick(name=msg.name))
        return Ticks(server=ctx.get(ServerInfo).address)

    @handler
    async def stop_timer(self, msg: StopTimer, ctx: AppData) -> Ticks:
        return Ticks(timer_ticks=self.timer_ticks, stopped=self.cancel_timer(msg.name))

    @handler
    async def tick(self, msg: TimerTick, ctx: AppData) -> Ticks:
        self.timer_ticks += 1
        return Ticks(timer_ticks=self.timer_ticks)

    @handler
    async def start_reminder(self, msg: StartReminder, ctx: AppData) -> Ticks:
        await self.register_reminder(
            ctx, msg.name, msg.period, first_due=time.time() + msg.first_in
        )
        return Ticks(server=ctx.get(ServerInfo).address)

    @handler
    async def poke(self, msg: Poke, ctx: AppData) -> Ticks:
        if msg.mode == "panic":
            raise ValueError("handler panic")
        if msg.mode == "shutdown":
            await self.shutdown(ctx)
        return Ticks(timer_ticks=self.timer_ticks, server=ctx.get(ServerInfo).address)

    async def receive_reminder(self, fired: ReminderFired, ctx: AppData) -> None:
        RECORD[fired.name].append(
            (ctx.get(ServerInfo).address, fired.missed, time.time())
        )


def build_registry() -> Registry:
    return Registry().add_type(Waker)


def fast_client(cluster: Cluster):
    c = cluster.client()
    c._backoff = ExponentialBackoff(initial=1e-4, cap=1e-2, max_retries=8)
    return c


def reminder_cluster_kwargs(storage: LocalReminderStorage, **cfg) -> dict:
    config = ReminderDaemonConfig(
        poll_interval=cfg.pop("poll_interval", 0.05),
        lease_ttl=cfg.pop("lease_ttl", 2.0),
        delivery_backoff=ExponentialBackoff(initial=1e-3, cap=0.05, max_retries=4),
        **cfg,
    )
    return dict(
        server_kwargs={"reminder_daemon": True, "reminder_daemon_config": config},
        app_data_builder=lambda: AppData().set(storage, as_type=ReminderStorage),
    )


async def wait_until(pred, timeout: float, interval: float = 0.02):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        v = pred()
        if v:
            return v
        await asyncio.sleep(interval)
    raise AssertionError(f"condition never became true within {timeout}s")


# ---------------------------------------------------------------------------
# volatile timers
# ---------------------------------------------------------------------------


def test_volatile_timer_fires_and_cancels():
    async def body(cluster: Cluster):
        client = fast_client(cluster)
        await client.send(Waker, "w1", StartTimer(name="t", period=0.03), returns=Ticks)
        # Ticks arrive through the normal dispatch queue.
        out = await wait_until_ticks(client, "w1", 3)
        # Cancel stops it; the count freezes.
        stop = await client.send(Waker, "w1", StopTimer(name="t"), returns=Ticks)
        assert stop.stopped
        frozen = stop.timer_ticks
        await asyncio.sleep(0.15)
        after = await client.send(Waker, "w1", StopTimer(name="absent"), returns=Ticks)
        assert after.timer_ticks == frozen >= out.timer_ticks >= 3
        assert not after.stopped  # cancelling a non-timer reports False
        client.close()

    async def wait_until_ticks(client, oid, n):
        for _ in range(200):
            out = await client.send(Waker, oid, StopTimer(name="absent"), returns=Ticks)
            if out.timer_ticks >= n:
                return out
            await asyncio.sleep(0.02)
        raise AssertionError(f"never saw {n} timer ticks")

    asyncio.run(run_integration_test(body, registry_builder=build_registry, num_servers=1))


def test_timer_cancelled_on_shutdown_and_panic():
    """Deactivation must kill timers on BOTH exits: the graceful SHUTDOWN
    lifecycle and the panic deallocation — an orphaned timer would keep
    re-activating the object through the dispatch queue forever."""

    async def body(cluster: Cluster):
        client = fast_client(cluster)
        # Graceful: shutdown from inside a handler (admin path).
        await client.send(Waker, "g1", StartTimer(period=0.03), returns=Ticks)
        await client.send(Waker, "g1", Poke(mode="shutdown"), returns=Ticks)
        await wait_until(
            lambda: not any(s.registry.has("Waker", "g1") for s in cluster.servers), 2.0
        )
        await asyncio.sleep(0.2)  # > several periods
        assert not any(s.registry.has("Waker", "g1") for s in cluster.servers), (
            "an orphaned timer re-activated the shut-down object"
        )

        # Panic: the deallocated instance's timers must die with it.
        await client.send(Waker, "p1", StartTimer(period=0.03), returns=Ticks)
        from rio_tpu.errors import ClientError

        with pytest.raises(ClientError):
            await client.send(Waker, "p1", Poke(mode="panic"), returns=Ticks)
        await asyncio.sleep(0.2)
        assert not any(s.registry.has("Waker", "p1") for s in cluster.servers), (
            "an orphaned timer re-activated the panicked object"
        )
        client.close()

    asyncio.run(run_integration_test(body, registry_builder=build_registry, num_servers=1))


# ---------------------------------------------------------------------------
# durable reminders through the cluster
# ---------------------------------------------------------------------------


def test_reminder_fires_through_cluster():
    storage = LocalReminderStorage()
    RECORD.pop("cluster-r", None)

    async def body(cluster: Cluster):
        client = fast_client(cluster)
        await client.send(
            Waker, "c1", StartReminder(name="cluster-r", period=0.1, first_in=0.1),
            returns=Ticks,
        )
        # Periodic delivery: several ticks, each on a live node, missed == 0
        # on a healthy schedule.
        await wait_until(lambda: len(RECORD["cluster-r"]) >= 3, 10.0)
        addrs = {a for a, _, _ in RECORD["cluster-r"]}
        assert addrs <= set(cluster.addresses)
        assert all(m == 0 for _, m, _ in RECORD["cluster-r"][:3])
        # The shard is seated in the directory through ObjectPlacement.
        shard = storage.shard_for("Waker", "c1")
        owner = await cluster.placement.lookup(ObjectId(SHARD_TYPE, str(shard)))
        assert owner in cluster.addresses
        # Unregister stops the schedule.
        r = await client.send(Waker, "c1", Poke(), returns=Ticks)
        assert r.server in cluster.addresses
        obj = next(s.registry.get("Waker", "c1") for s in cluster.servers
                   if s.registry.has("Waker", "c1"))
        sa = next(s for s in cluster.servers if s.registry.has("Waker", "c1"))
        await obj.unregister_reminder(sa.app_data, "cluster-r")
        await asyncio.sleep(0.1)
        n = len(RECORD["cluster-r"])
        await asyncio.sleep(0.4)
        assert len(RECORD["cluster-r"]) <= n + 1  # at most one in-flight tick
        client.close()

    asyncio.run(
        run_integration_test(
            body, registry_builder=build_registry, num_servers=2, timeout=30.0,
            **reminder_cluster_kwargs(storage),
        )
    )


def _find_server(cluster: Cluster, address: str):
    return next(s for s in cluster.servers if s.local_address == address)


def test_reminder_failover_on_server_kill():
    """A reminder registered via node A keeps firing on the survivor within
    one lease interval after the shard owner dies (acceptance criterion).
    The dead owner never releases its lease, so the gap is bounded by
    lease_ttl; the first post-takeover tick carries the missed count."""
    storage = LocalReminderStorage()
    RECORD.pop("kill-r", None)
    lease_ttl = 2.0

    async def body(cluster: Cluster):
        client = fast_client(cluster)
        await client.send(
            Waker, "k1", StartReminder(name="kill-r", period=0.1, first_in=0.1),
            returns=Ticks,
        )
        await wait_until(lambda: len(RECORD["kill-r"]) >= 2, 10.0)

        shard = storage.shard_for("Waker", "k1")
        owner = await cluster.placement.lookup(ObjectId(SHARD_TYPE, str(shard)))
        assert owner in cluster.addresses
        # Kill the shard-owning server (unannounced as far as the reminder
        # subsystem goes — no drain, its lease stays in storage).
        _find_server(cluster, owner).admin_sender().send(AdminCommand.server_exit())
        t_kill = time.time()

        def survivor_tick():
            return next(
                (
                    (a, m, ts)
                    for a, m, ts in RECORD["kill-r"]
                    if a != owner and ts > t_kill
                ),
                None,
            )

        tick = await wait_until(survivor_tick, 15.0)
        addr, missed, ts = tick
        assert addr in cluster.addresses and addr != owner
        # Within one lease interval (plus poll/delivery slack).
        assert ts - t_kill <= lease_ttl + 2.0, (
            f"failover took {ts - t_kill:.2f}s (lease_ttl={lease_ttl})"
        )
        # Catch-up: the outage spanned multiple periods; the first
        # post-takeover tick reports them.
        assert missed >= 1
        # The schedule keeps running on the survivor afterwards.
        n = len(RECORD["kill-r"])
        await wait_until(lambda: len(RECORD["kill-r"]) >= n + 2, 10.0)
        client.close()

    asyncio.run(
        run_integration_test(
            body, registry_builder=build_registry, num_servers=2, timeout=40.0,
            **reminder_cluster_kwargs(storage, lease_ttl=lease_ttl),
        )
    )


def test_reminder_failover_on_graceful_drain():
    """DRAIN_SERVER hands shards off: the daemon releases its leases and
    directory seats before exit, so the survivor resumes ticking without
    waiting out the lease TTL (acceptance criterion, graceful half)."""
    storage = LocalReminderStorage()
    RECORD.pop("drain-r", None)

    async def body(cluster: Cluster):
        client = fast_client(cluster)
        await client.send(
            Waker, "d1", StartReminder(name="drain-r", period=0.1, first_in=0.1),
            returns=Ticks,
        )
        await wait_until(lambda: len(RECORD["drain-r"]) >= 2, 10.0)

        shard = storage.shard_for("Waker", "d1")
        owner = await cluster.placement.lookup(ObjectId(SHARD_TYPE, str(shard)))
        _find_server(cluster, owner).admin_sender().send(AdminCommand.drain())
        t_drain = time.time()

        tick = await wait_until(
            lambda: next(
                (
                    (a, m, ts)
                    for a, m, ts in RECORD["drain-r"]
                    if a != owner and ts > t_drain
                ),
                None,
            ),
            15.0,
        )
        _, _, ts = tick
        # Released leases make the handoff prompt — well under the TTL-expiry
        # bound the kill test tolerates.
        assert ts - t_drain <= 4.0
        # The released lease was re-acquired by the survivor, epoch advanced.
        lease = await storage.get_lease(shard)
        assert lease is not None and lease.owner != owner
        client.close()

    asyncio.run(
        run_integration_test(
            body, registry_builder=build_registry, num_servers=2, timeout=40.0,
            **reminder_cluster_kwargs(storage, lease_ttl=5.0),
        )
    )


def test_reminder_fires_through_shard_migration():
    """Fire a reminder WHILE its shard seat migrates (twice, there and
    back, through the same ``apply_moves`` path the rebalancer uses).

    The shard row has no live activation, so the migration is a directory
    flip racing the old owner's poll loop; the lease is what serializes
    the two daemons across that race. Contract: no double-fire (no two
    deliveries of one due slot) and no missed tick (``fired.missed`` stays
    0 — the schedule never skipped a period) across both handoffs.
    """
    storage = LocalReminderStorage()
    RECORD.pop("mig-r", None)
    period = 0.25

    async def body(cluster: Cluster):
        client = fast_client(cluster)
        await client.send(
            Waker, "m1", StartReminder(name="mig-r", period=period, first_in=0.1),
            returns=Ticks,
        )
        await wait_until(lambda: len(RECORD["mig-r"]) >= 2, 10.0)
        shard = storage.shard_for("Waker", "m1")
        key = f"{SHARD_TYPE}.{shard}"

        # NOTE RECORD addresses name the node hosting the Waker ACTOR (it
        # never moves here); which daemon delivered is visible through the
        # lease owner and each daemon's tick counter.
        for _ in range(2):  # there and back again
            owner = await cluster.placement.lookup(ObjectId(SHARD_TYPE, str(shard)))
            assert owner in cluster.addresses
            other = next(a for a in cluster.addresses if a != owner)
            mover = _find_server(cluster, owner)
            new_daemon = _find_server(cluster, other).reminder_daemon
            ticks_before = new_daemon.stats.ticks
            # Ticking continues while the seat row rides apply_moves.
            moved = await mover.migration_manager.apply_moves([(key, owner, other)])
            assert moved == 1
            # Delivery resumes from the NEW owner's daemon without waiting
            # out the lease TTL (the old daemon releases on seeing the
            # flipped seat) and without the old daemon stealing the seat
            # back (the handoff grace in ``_seat_is_stale``).
            await wait_until(
                lambda: new_daemon.stats.ticks > ticks_before, 10.0
            )
            lease = await storage.get_lease(shard)
            assert lease is not None and lease.owner == other
            seat = await cluster.placement.lookup(ObjectId(SHARD_TYPE, str(shard)))
            assert seat == other

        ticks = RECORD["mig-r"]
        # No missed tick: every delivery ran within one period of its due
        # time, including the ones straddling the handoffs.
        assert all(m == 0 for _, m, _ in ticks), ticks
        # No double-fire: the lease serialized the daemons, so no due slot
        # was delivered twice — any pair of deliveries is at least a good
        # fraction of a period apart.
        stamps = sorted(ts for _, _, ts in ticks)
        gaps = [b - a for a, b in zip(stamps, stamps[1:])]
        assert all(g > period / 4 for g in gaps), gaps
        client.close()

    asyncio.run(
        run_integration_test(
            body, registry_builder=build_registry, num_servers=2, timeout=40.0,
            **reminder_cluster_kwargs(storage),
        )
    )


# ---------------------------------------------------------------------------
# daemon-level determinism: catch-up policies + at-least-once
# ---------------------------------------------------------------------------


class StubClient:
    """Records deliveries; optionally fails the first N with a transport
    error (the daemon must treat those as undelivered)."""

    def __init__(self, fail_first: int = 0):
        self.sent: list[tuple[str, str, ReminderFired]] = []
        self.fail_first = fail_first

    async def send(self, kind, oid, msg, returns=None):
        if self.fail_first > 0:
            self.fail_first -= 1
            from rio_tpu.errors import Disconnect

            raise Disconnect("stub transport down")
        self.sent.append((kind, oid, msg))

    def close(self):
        pass


async def _one_node_daemon(storage, client, **cfg):
    members = LocalStorage()
    await members.push(Member(ip="10.0.0.1", port=9000, active=True))
    daemon = ReminderDaemon(
        address="10.0.0.1:9000",
        members_storage=members,
        placement=LocalObjectPlacement(),
        storage=storage,
        config=ReminderDaemonConfig(**cfg),
        client=client,
    )
    return daemon


@pytest.mark.asyncio
async def test_catchup_skip_jumps_phase_aligned():
    storage = LocalReminderStorage(num_shards=4)
    await storage.upsert(Reminder("Svc", "a", "r", period=10.0, next_due=100.0))
    shard = storage.shard_for("Svc", "a")
    client = StubClient()
    daemon = await _one_node_daemon(storage, client, catchup="skip", lease_ttl=60.0)

    await daemon.poll_once(now=135.0)  # 3 whole periods missed
    assert len(client.sent) == 1
    fired = client.sent[0][2]
    assert (fired.name, fired.due, fired.missed) == ("r", 100.0, 3)
    # Phase-aligned jump: 100 + (3+1)*10, NOT "now + period".
    assert (await storage.list_object("Svc", "a"))[0].next_due == 140.0
    assert daemon.stats.ticks == 1 and daemon.stats.missed_ticks == 3
    # Not due again until 140.
    await daemon.poll_once(now=139.0)
    assert len(client.sent) == 1
    # The daemon seated the shard through the placement directory.
    assert await daemon.placement.lookup(
        ObjectId(SHARD_TYPE, str(shard))
    ) == "10.0.0.1:9000"


@pytest.mark.asyncio
async def test_catchup_all_replays_every_missed_tick():
    storage = LocalReminderStorage(num_shards=4)
    await storage.upsert(Reminder("Svc", "a", "r", period=10.0, next_due=100.0))
    client = StubClient()
    daemon = await _one_node_daemon(storage, client, catchup="all", lease_ttl=60.0)

    for _ in range(6):  # more polls than backlog; extras must not over-fire
        await daemon.poll_once(now=135.0)
    # Every schedule point in (100..135] fired exactly once: 100,110,120,130.
    assert [(m.due, m.missed) for _, _, m in client.sent] == [
        (100.0, 3), (110.0, 2), (120.0, 1), (130.0, 0)
    ]
    assert (await storage.list_object("Svc", "a"))[0].next_due == 140.0


@pytest.mark.asyncio
async def test_at_least_once_on_transport_failure():
    storage = LocalReminderStorage(num_shards=4)
    await storage.upsert(Reminder("Svc", "a", "r", period=10.0, next_due=100.0))
    client = StubClient(fail_first=2)
    daemon = await _one_node_daemon(storage, client, lease_ttl=60.0)

    # Two failed polls: undelivered, next_due untouched, failure counted.
    await daemon.poll_once(now=105.0)
    await daemon.poll_once(now=106.0)
    assert client.sent == [] and daemon.stats.delivery_failures == 2
    assert (await storage.list_object("Svc", "a"))[0].next_due == 100.0
    # Transport back: the SAME tick is delivered, then rescheduled.
    await daemon.poll_once(now=107.0)
    assert len(client.sent) == 1 and client.sent[0][2].due == 100.0
    assert (await storage.list_object("Svc", "a"))[0].next_due == 110.0


@pytest.mark.asyncio
async def test_handler_error_counts_as_delivered():
    """An application-level failure must NOT hot-loop the tick each poll."""

    class AngryClient(StubClient):
        async def send(self, kind, oid, msg, returns=None):
            self.sent.append((kind, oid, msg))
            raise RuntimeError("handler blew up")

    storage = LocalReminderStorage(num_shards=4)
    await storage.upsert(Reminder("Svc", "a", "r", period=10.0, next_due=100.0))
    client = AngryClient()
    daemon = await _one_node_daemon(storage, client, lease_ttl=60.0)
    await daemon.poll_once(now=105.0)
    assert len(client.sent) == 1 and daemon.stats.delivery_failures == 0
    assert (await storage.list_object("Svc", "a"))[0].next_due == 110.0


@pytest.mark.asyncio
async def test_daemon_steals_stale_seat_on_live_non_ticking_node():
    """A solver rebalance can seat a shard on a live node that runs no
    reminder daemon. Once the lease lapses a full TTL past expiry (or was
    never taken), any daemon may steal through the lease and move the seat
    to itself — otherwise the shard would never tick again."""
    storage = LocalReminderStorage(num_shards=4)
    await storage.upsert(Reminder("Svc", "a", "r", period=10.0, next_due=100.0))
    shard = storage.shard_for("Svc", "a")
    members = LocalStorage()
    await members.push(Member(ip="10.0.0.1", port=9000, active=True))
    await members.push(Member(ip="10.0.0.2", port=9000, active=True))
    client = StubClient()
    daemon = ReminderDaemon(
        address="10.0.0.1:9000",
        members_storage=members,
        placement=LocalObjectPlacement(),
        storage=storage,
        config=ReminderDaemonConfig(lease_ttl=10.0),
        client=client,
    )
    oid = ObjectId(SHARD_TYPE, str(shard))
    # Seat the shard on the live daemon-less node, lease held there too.
    await daemon.placement.update(ObjectPlacementItem(object_id=oid, server_address="10.0.0.2:9000"))
    lease = await storage.acquire_lease(shard, "10.0.0.2:9000", ttl=10.0, now=100.0)
    assert lease is not None
    # Lease valid: the seat is respected, nothing fires from us.
    await daemon.poll_once(now=105.0)
    assert client.sent == [] and await daemon.placement.lookup(oid) == "10.0.0.2:9000"
    # Expired but within one TTL of grace: still not stealable (renewal lag).
    await daemon.poll_once(now=115.0)
    assert client.sent == [] and await daemon.placement.lookup(oid) == "10.0.0.2:9000"
    # Lapsed a full TTL past expiry: provably not ticking — steal and tick.
    await daemon.poll_once(now=121.0)
    assert await daemon.placement.lookup(oid) == "10.0.0.1:9000"
    assert len(client.sent) == 1 and shard in daemon._held
    stolen = await storage.get_lease(shard)
    assert stolen.owner == "10.0.0.1:9000" and stolen.epoch > lease.epoch


@pytest.mark.asyncio
async def test_daemon_respects_foreign_lease_and_handoff():
    storage = LocalReminderStorage(num_shards=4)
    await storage.upsert(Reminder("Svc", "a", "r", period=10.0, next_due=100.0))
    shard = storage.shard_for("Svc", "a")
    # Another node holds the shard's lease (unexpired).
    foreign = await storage.acquire_lease(shard, "10.0.0.2:9000", ttl=1000.0, now=100.0)
    assert foreign is not None
    client = StubClient()
    daemon = await _one_node_daemon(storage, client, lease_ttl=60.0)
    await daemon.poll_once(now=105.0)
    # Directory seated us (nothing else claimed it) but the lease blocks
    # ticking — exactly-one-node-ticks is the lease's job, not the seat's.
    assert client.sent == [] and shard not in daemon._held
    # Foreign owner releases (drain); our next poll acquires and ticks.
    await storage.release_lease(shard, "10.0.0.2:9000", foreign.epoch)
    await daemon.poll_once(now=106.0)
    assert len(client.sent) == 1 and shard in daemon._held
    # Our own handoff frees lease + seat for the next owner.
    await daemon.handoff()
    lease = await storage.get_lease(shard)
    assert lease is not None and lease.expires_at == 0.0
    assert await daemon.placement.lookup(ObjectId(SHARD_TYPE, str(shard))) is None
