"""KIND_COMMAND wire layer: golden frames, old-server compat, live commands.

Three contracts:

* **golden frames** — the exact bytes every stream/saga command puts on
  the wire, committed under ``tests/golden/`` (regenerate intentionally
  with ``RIO_TPU_REGEN_GOLDEN=1``). A drift here is a wire break for
  mixed-version clusters and has to be a conscious decision.
* **old-server story** — a frame kind the server doesn't speak (or, on a
  pre-streams server, a command it can't service) answers a clean
  NOT_SUPPORTED response; the connection survives and later requests on
  it still work. No resets, ever.
* **live commands** — the remote producer/consumer/saga APIs
  (``Client.publish_stream`` & co.) against a real cluster.
"""

from __future__ import annotations

import asyncio
import difflib
import os
import pathlib
from collections import defaultdict

import pytest

from rio_tpu import AppData, Registry, ServiceObject, codec, handler, message
from rio_tpu.errors import ClientError
from rio_tpu.protocol import (
    ErrorKind,
    RequestEnvelope,
    UnknownFrameKind,
    decode_inbound,
    decode_response,
    encode_command_frame,
    encode_request_frame,
    CommandEnvelope,
)
from rio_tpu.state import LocalState, StateProvider
from rio_tpu.streams import LocalStreamStorage, StreamDelivery, StreamStorage
from rio_tpu.streams.saga import SAGA_TYPE, SagaStatus, StartSaga, step

from .server_utils import Cluster, run_integration_test

GOLDEN_DIR = pathlib.Path(__file__).parent / "golden"


# ---------------------------------------------------------------------------
# golden frames
# ---------------------------------------------------------------------------


@message
class Note:
    text: str = ""


def _command_matrix() -> list[tuple[str, bytes]]:
    """Every new wire command, with deterministic payloads."""
    note = codec.serialize(Note(text="hi"))
    trace = ("ab" * 16, "cd" * 8, True)
    saga_steps = [step("Account", "a", Note(text="go"), Note(text="undo"))]
    matrix = [
        (
            "stream.publish",
            CommandEnvelope(
                "stream.publish",
                "orders",
                codec.serialize(["orders", "k1", "Note", note]),
            ),
        ),
        (
            "stream.publish traced",
            CommandEnvelope(
                "stream.publish",
                "orders",
                codec.serialize(["orders", "k1", "Note", note]),
                trace,
            ),
        ),
        (
            "stream.subscribe",
            CommandEnvelope(
                "stream.subscribe", "orders", codec.serialize(["g1", "Sink", 2.0])
            ),
        ),
        (
            "stream.unsubscribe",
            CommandEnvelope("stream.unsubscribe", "orders", codec.serialize(["g1"])),
        ),
        (
            "stream.cursors",
            CommandEnvelope("stream.cursors", "orders", codec.serialize(["g1"])),
        ),
        (
            "saga.start",
            CommandEnvelope(
                "saga.start",
                "order-1",
                codec.serialize(StartSaga(steps=saga_steps)),
            ),
        ),
        (
            "saga.status",
            CommandEnvelope("saga.status", "order-1", codec.serialize(SagaStatus())),
        ),
    ]
    return [(name, encode_command_frame(env)) for name, env in matrix]


def test_command_frames_golden():
    lines = [f"{name}: {frame.hex()}" for name, frame in _command_matrix()]
    text = "\n".join(lines) + "\n"
    path = GOLDEN_DIR / "command_frames.txt"
    if os.environ.get("RIO_TPU_REGEN_GOLDEN"):
        path.write_text(text)
        return
    assert path.exists(), (
        f"missing golden file {path} — run with RIO_TPU_REGEN_GOLDEN=1 to create"
    )
    expected = path.read_text()
    if text != expected:
        diff = "\n".join(
            difflib.unified_diff(
                expected.splitlines(), text.splitlines(),
                fromfile="golden/command_frames.txt", tofile="captured",
                lineterm="",
            )
        )
        raise AssertionError(f"command wire drifted:\n{diff}")


def test_command_envelope_roundtrip():
    for _, frame in _command_matrix():
        env = decode_inbound(frame[4:])
        assert type(env) is CommandEnvelope
        assert encode_command_frame(env) == frame
    # Untraced frames omit the trace field entirely (3-element layout),
    # byte-identical to a legacy encoder that never heard of tracing.
    untraced = CommandEnvelope("stream.cursors", "s", b"")
    assert untraced.to_bytes() == codec.serialize(["stream.cursors", "s", b""])


def test_unknown_frame_kind_is_typed():
    with pytest.raises(UnknownFrameKind):
        decode_inbound(b"\x7fjunk")
    # Empty / malformed frames stay generic SerializationError — only a
    # recognizably-framed-but-unknown kind takes the NOT_SUPPORTED path.
    from rio_tpu.errors import SerializationError

    with pytest.raises(SerializationError) as ei:
        decode_inbound(b"")
    assert not isinstance(ei.value, UnknownFrameKind)


# ---------------------------------------------------------------------------
# live cluster: client command APIs
# ---------------------------------------------------------------------------

SEEN: dict[str, list[tuple]] = defaultdict(list)


class CmdSink(ServiceObject):
    async def receive_stream(self, delivery: StreamDelivery, ctx) -> None:
        SEEN[self.id].append(
            (delivery.group, delivery.offset, delivery.decode(Note).text)
        )


class CmdAccount(ServiceObject):
    @handler
    async def note(self, msg: Note, ctx) -> str:
        return msg.text


def build_registry() -> Registry:
    return Registry().add_type(CmdSink).add_type(CmdAccount)


def _streams_app_data():
    storage = LocalStreamStorage()
    state = LocalState()

    def build() -> AppData:
        return (
            AppData()
            .set(storage, as_type=StreamStorage)
            .set(state, as_type=StateProvider)
        )

    return storage, build


async def wait_until(pred, timeout: float, interval: float = 0.02):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if pred():
            return
        await asyncio.sleep(interval)
    raise AssertionError(f"condition never became true within {timeout}s")


def test_client_stream_commands_end_to_end():
    """Remote producer/consumer management purely over KIND_COMMAND."""
    SEEN.clear()
    storage, app_data = _streams_app_data()

    async def body(cluster: Cluster):
        client = cluster.client()
        await client.subscribe_stream("orders", "audit", CmdSink)
        acks = [
            await client.publish_stream("orders", Note(text=f"n{i}"), key="k")
            for i in range(5)
        ]
        partition = storage.partition_of("orders", "k")
        assert [o for _, o in acks] == [0, 1, 2, 3, 4]
        assert all(p == partition for p, _ in acks)

        def delivered():
            return sum(len(v) for v in SEEN.values()) == 5

        await wait_until(delivered, 10.0)
        rows = [r for v in SEEN.values() for r in v]
        assert sorted(r[1] for r in rows) == [0, 1, 2, 3, 4]
        cursors = await client.stream_cursors("orders", "audit")
        assert cursors.get(partition) == 5
        await client.unsubscribe_stream("orders", "audit")
        assert await storage.subscriptions("orders") == []
        client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=2,
            app_data_builder=app_data,
        )
    )


def test_client_saga_commands():
    _, app_data = _streams_app_data()

    async def body(cluster: Cluster):
        client = cluster.client()
        reply = await client.start_saga(
            "cmd-saga-1",
            [step(CmdAccount, "a", Note(text="go"), Note(text="undo"))],
        )
        assert reply.status == "completed", reply
        status = await client.saga_status("cmd-saga-1")
        assert status.status == "completed" and status.total == 1
        client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=2,
            app_data_builder=app_data,
        )
    )


def test_unknown_command_and_missing_backend_answer_not_supported():
    """A verb the server doesn't know — and a stream command on a server
    with no StreamStorage — both come back NOT_SUPPORTED, not a reset."""
    _, app_data = _streams_app_data()

    async def body(cluster: Cluster):
        client = cluster.client()
        with pytest.raises(ClientError, match="NOT_SUPPORTED"):
            await client.send_command("stream.compact", "orders", b"")
        # The connection pool survived: a real command still works after.
        await client.subscribe_stream("orders", "g", CmdSink)
        client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=1,
            app_data_builder=app_data,
        )
    )

    async def bare_body(cluster: Cluster):
        client = cluster.client()
        with pytest.raises(ClientError, match="NOT_SUPPORTED"):
            await client.publish_stream("orders", Note(text="x"))
        client.close()

    asyncio.run(
        run_integration_test(
            bare_body, registry_builder=build_registry, num_servers=1
        )
    )


def test_unknown_frame_kind_survives_connection():
    """The old-server story, at the socket level: an unrecognized frame
    kind answers NOT_SUPPORTED in FIFO position, and a pipelined valid
    request on the SAME connection is still answered."""
    _, app_data = _streams_app_data()

    async def body(cluster: Cluster):
        host, _, port = cluster.addresses[0].rpartition(":")
        reader, writer = await asyncio.open_connection(host, int(port))
        # Pipeline: bogus kind 0x7f, then a valid saga status request.
        writer.write(codec.frame(b"\x7f" + b"not-a-real-frame"))
        writer.write(
            encode_request_frame(
                RequestEnvelope(
                    SAGA_TYPE, "ghost", "rio.SagaStatus",
                    codec.serialize(SagaStatus()),
                )
            )
        )
        await writer.drain()

        async def read_frame() -> bytes:
            header = await reader.readexactly(4)
            return await reader.readexactly(int.from_bytes(header, "big"))

        first = decode_response(await read_frame())
        assert not first.is_ok
        assert first.error.kind == ErrorKind.NOT_SUPPORTED
        assert "unknown frame kind" in first.error.detail
        second = decode_response(await read_frame())
        assert second.is_ok  # idle saga reports cleanly — conn survived
        writer.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=1,
            app_data_builder=app_data,
        )
    )
