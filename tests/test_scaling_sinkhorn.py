"""Parity: scaling-form (Sinkhorn-Knopp) solvers vs the log-domain solve.

The scaling iterations are mathematically identical to the log-domain
updates, so with a float32 kernel the potentials must agree tightly; the
fused Pallas version (interpret mode on the CPU test mesh) must agree with
the XLA scaling version bit-for-mathematically."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rio_tpu.ops.scaling import (
    fused_scaling_iteration,
    pallas_scaling_sinkhorn,
    scaling_sinkhorn,
)
from rio_tpu.ops.sinkhorn import plan_rounded_assign, sinkhorn


def _problem(key, n, m, dead_nodes=0, padded_rows=0):
    k1, k2, k3 = jax.random.split(key, 3)
    cost = jax.random.uniform(k1, (n, m), jnp.float32)
    mass = jax.random.uniform(k2, (n,), jnp.float32) + 0.1
    if padded_rows:
        mass = mass.at[-padded_rows:].set(0.0)
    cap = jax.random.uniform(k3, (m,), jnp.float32) + 0.5
    if dead_nodes:
        cap = cap.at[:dead_nodes].set(0.0)
    return cost, mass, cap


@pytest.mark.parametrize("n,m", [(64, 128), (96, 130)])
def test_scaling_matches_log_domain(n, m):
    cost, mass, cap = _problem(jax.random.PRNGKey(0), n, m)
    ref = sinkhorn(cost, mass, cap, eps=0.08, n_iters=25)
    out = scaling_sinkhorn(
        cost, mass, cap, eps=0.08, n_iters=25, kernel_dtype=jnp.float32
    )
    np.testing.assert_allclose(np.asarray(out.f), np.asarray(ref.f), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(out.g), np.asarray(ref.g), rtol=1e-3, atol=1e-3)


def test_scaling_matches_log_domain_offset_costs():
    """f-parity must survive costs with a negative / shifted minimum.

    The scaling solvers gauge-shift by min(cost) internally; the shift must
    be folded back into f (the hierarchical mode's normalized -(feat@feat)
    costs have a negative min, where an unshifted f would diverge from the
    log-domain reference by -min(cost))."""
    cost, mass, cap = _problem(jax.random.PRNGKey(7), 64, 96)
    cost = cost * 2.0 - 1.7  # min well below zero
    ref = sinkhorn(cost, mass, cap, eps=0.08, n_iters=25)
    for solver in (
        lambda: scaling_sinkhorn(
            cost, mass, cap, eps=0.08, n_iters=25, kernel_dtype=jnp.float32
        ),
        lambda: pallas_scaling_sinkhorn(
            cost, mass, cap, eps=0.08, n_iters=25,
            kernel_dtype=jnp.float32, block_rows=16,
        ),
    ):
        out = solver()
        np.testing.assert_allclose(
            np.asarray(out.f), np.asarray(ref.f), rtol=1e-3, atol=1e-3
        )
        np.testing.assert_allclose(
            np.asarray(out.g), np.asarray(ref.g), rtol=1e-3, atol=1e-3
        )


def test_sharded_scaling_offset_costs_f_parity():
    from rio_tpu.parallel import make_mesh, sharded_scaling_sinkhorn

    mesh = make_mesh(jax.devices()[:8])
    cost, mass, cap = _problem(jax.random.PRNGKey(8), 64, 96)
    cost = cost - 0.9
    ref = sinkhorn(cost, mass, cap, eps=0.08, n_iters=25)
    f, g = sharded_scaling_sinkhorn(
        mesh, cost, mass, cap, eps=0.08, n_iters=25, kernel_dtype=jnp.float32
    )
    np.testing.assert_allclose(np.asarray(f), np.asarray(ref.f), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(g), np.asarray(ref.g), rtol=1e-3, atol=1e-3)


def test_scaling_dead_nodes_and_padding():
    cost, mass, cap = _problem(jax.random.PRNGKey(1), 48, 96, dead_nodes=3, padded_rows=5)
    ref = sinkhorn(cost, mass, cap, eps=0.06, n_iters=30)
    out = scaling_sinkhorn(cost, mass, cap, eps=0.06, n_iters=30, kernel_dtype=jnp.float32)
    assert np.all(np.isneginf(np.asarray(out.g[:3])))
    assert np.all(np.isneginf(np.asarray(out.f[-5:])))
    np.testing.assert_allclose(np.asarray(out.g[3:]), np.asarray(ref.g[3:]), rtol=1e-3, atol=1e-3)
    a1 = plan_rounded_assign(cost, out.f, out.g, 0.06)
    a2 = plan_rounded_assign(cost, ref.f, ref.g, 0.06)
    assert np.mean(np.asarray(a1) == np.asarray(a2)) > 0.95


@pytest.mark.parametrize("n,m,block", [(64, 128, 8), (96, 130, 32), (40, 100, 16)])
def test_pallas_scaling_matches_xla_scaling(n, m, block):
    cost, mass, cap = _problem(jax.random.PRNGKey(2), n, m)
    ref = scaling_sinkhorn(cost, mass, cap, eps=0.07, n_iters=25, kernel_dtype=jnp.float32)
    out = pallas_scaling_sinkhorn(
        cost, mass, cap, eps=0.07, n_iters=25,
        kernel_dtype=jnp.float32, block_rows=block, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out.f), np.asarray(ref.f), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out.g), np.asarray(ref.g), rtol=1e-4, atol=1e-4)


def test_pallas_scaling_bf16_close_enough_for_assignment():
    cost, mass, cap = _problem(jax.random.PRNGKey(3), 128, 128)
    ref = sinkhorn(cost, mass, cap, eps=0.08, n_iters=25)
    out = pallas_scaling_sinkhorn(
        cost, mass, cap, eps=0.08, n_iters=25,
        kernel_dtype=jnp.bfloat16, block_rows=32, interpret=True,
    )
    a1 = plan_rounded_assign(cost, out.f, out.g, 0.08)
    a2 = plan_rounded_assign(cost, ref.f, ref.g, 0.08)
    # bf16 kernel may flip near-ties; the bulk of the assignment must agree.
    assert np.mean(np.asarray(a1) == np.asarray(a2)) > 0.9


def test_fused_scaling_iteration_single_step():
    n, m = 32, 128
    key = jax.random.PRNGKey(4)
    K = jax.random.uniform(key, (n, m), jnp.float32) + 0.01
    a = jnp.full((n,), 1.0 / n)
    b = jnp.full((m,), 1.0 / m)
    v_prev = jax.random.uniform(jax.random.PRNGKey(5), (m,)) + 0.5
    u, v = fused_scaling_iteration(K, a, b, v_prev, block_rows=8, interpret=True)
    u_ref = a / (K @ v_prev)
    v_ref = b / (K.T @ u_ref)
    np.testing.assert_allclose(np.asarray(u), np.asarray(u_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(v), np.asarray(v_ref), rtol=1e-5)


def test_sharded_scaling_matches_single_device():
    import pytest

    if len(jax.devices()) < 8:
        pytest.skip("needs 8-device mesh")
    from rio_tpu.parallel import make_mesh, shard_cost, sharded_scaling_sinkhorn

    n, m = 128, 64
    cost, mass, cap = _problem(jax.random.PRNGKey(6), n, m, dead_nodes=2)
    single = scaling_sinkhorn(
        cost, mass, cap, eps=0.07, n_iters=25, kernel_dtype=jnp.float32
    )
    mesh = make_mesh(jax.devices()[:8])
    f, g = sharded_scaling_sinkhorn(
        mesh, shard_cost(mesh, cost), mass, cap,
        eps=0.07, n_iters=25, kernel_dtype=jnp.float32,
    )
    np.testing.assert_allclose(np.asarray(g), np.asarray(single.g), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(f), np.asarray(single.f), rtol=1e-4, atol=1e-4)


def test_plan_rounding_from_scaling_state_matches_potential_form():
    """K-reuse rounding == potential-form rounding (the bench hot path).

    ``plan_rounded_assign_from_scaling`` reads the already-materialized
    bf16 kernel instead of re-deriving exp((f+g-C)/eps) from the fp32 cost;
    with a float32 kernel the assignments must be identical, with bfloat16
    near-identical and equally balanced.
    """
    import numpy as np

    from rio_tpu.ops import (
        plan_rounded_assign,
        plan_rounded_assign_from_scaling,
        scaling_core,
        scaling_sinkhorn,
    )

    key = jax.random.PRNGKey(3)
    n, m = 2048, 128
    cost = jax.random.uniform(key, (n, m))
    mass, cap = jnp.ones((n,)), jnp.ones((m,))
    kw = dict(eps=0.05, n_iters=25)

    res = scaling_sinkhorn(cost, mass, cap, kernel_dtype=jnp.float32, **kw)
    base = np.asarray(plan_rounded_assign(cost, res.f, res.g, 0.05))

    u, v, K, _ = scaling_core(cost, mass, cap, kernel_dtype=jnp.float32, **kw)
    exact = np.asarray(plan_rounded_assign_from_scaling(K, u, v))
    assert (exact == base).all()

    u, v, K, _ = scaling_core(cost, mass, cap, kernel_dtype=jnp.bfloat16, **kw)
    approx = np.asarray(plan_rounded_assign_from_scaling(K, u, v))
    assert (approx == base).mean() > 0.98
    loads_base = np.bincount(base, minlength=m)
    loads_approx = np.bincount(approx, minlength=m)
    assert abs(int(loads_approx.max()) - int(loads_base.max())) <= 2


def test_plan_rounding_from_scaling_padding_and_dead_columns():
    """Padding rows (u=0) spread over live columns; dead columns never chosen."""
    import numpy as np

    from rio_tpu.ops import plan_rounded_assign_from_scaling, scaling_core

    key = jax.random.PRNGKey(5)
    n, m, n_real = 512, 16, 300
    cost = jax.random.uniform(key, (n, m))
    mass = jnp.concatenate([jnp.ones((n_real,)), jnp.zeros((n - n_real,))])
    cap = jnp.concatenate([jnp.ones((m - 4,)), jnp.zeros((4,))])  # 4 dead
    u, v, K, _ = scaling_core(cost, mass, cap, eps=0.05, n_iters=25)
    idx = np.asarray(plan_rounded_assign_from_scaling(K, u, v))
    assert (idx[:n_real] < m - 4).all()  # real rows avoid dead columns
    assert (idx[n_real:] < m - 4).all()  # padding falls back to live columns


def test_scaling_survives_wide_cost_ranges():
    """Per-row gauge shift: no row underflows even when range/eps >> 88.

    Regression: with a GLOBAL min shift, rows whose best entry sits far
    above the global min lost every kernel entry to exp-underflow and their
    scaling exploded — observed as 37% bucket overflow in the 10M-object
    hierarchical tier (random-normal features, std-normalized cost,
    eps=0.05).
    """
    import numpy as np

    from rio_tpu.ops import scaling_sinkhorn, sinkhorn

    key = jax.random.PRNGKey(11)
    n, m = 8192, 64
    # Heavy-tailed rows: some rows sit 20+ sigma from the global min.
    cost = jax.random.normal(key, (n, m)) + 30.0 * jax.random.uniform(
        jax.random.PRNGKey(12), (n, 1)
    )
    cost = cost / jnp.std(cost)
    mass, cap = jnp.ones((n,)), jnp.ones((m,))
    res = scaling_sinkhorn(cost, mass, cap, eps=0.05, n_iters=40)
    assert bool(jnp.isfinite(res.err)), "marginal error must be finite"
    assert float(res.err) < 0.05 * n  # marginals approximately matched
    ref = sinkhorn(cost, mass, cap, eps=0.05, n_iters=40)
    finite = jnp.isfinite(res.g) & jnp.isfinite(ref.g)
    assert float(jnp.max(jnp.abs(res.g[finite] - ref.g[finite]))) < 5e-2


def test_pallas_scaling_core_matches_xla_core():
    """pallas_scaling_core is a drop-in for scaling_core: same (u, v, K, shift).

    This is the contract the r5 promotion rides on (scaling_core_auto swaps
    one for the other based on backend/shape): u/v must match within dtype
    tolerance, and the returned K must be the UNPADDED kernel the rounding
    pass reuses."""
    from rio_tpu.ops.scaling import pallas_scaling_core, scaling_core

    cost, mass, cap = _problem(
        jax.random.PRNGKey(21), 96, 130, dead_nodes=3, padded_rows=5
    )
    u_x, v_x, K_x, sh_x = scaling_core(
        cost, mass, cap, eps=0.07, n_iters=20, kernel_dtype=jnp.float32
    )
    u_p, v_p, K_p, sh_p = pallas_scaling_core(
        cost, mass, cap, eps=0.07, n_iters=20,
        kernel_dtype=jnp.float32, block_rows=16, interpret=True,
    )
    assert K_p.shape == cost.shape  # unpadded, reusable by rounding
    np.testing.assert_allclose(np.asarray(u_p), np.asarray(u_x), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(v_p), np.asarray(v_x), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(K_p), np.asarray(K_x), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(sh_p), np.asarray(sh_x), rtol=1e-6, atol=1e-6)


def test_scaling_core_auto_dispatch():
    """Off-TPU the dispatcher must pick XLA everywhere; the selection rule
    itself (bandwidth regime + block alignment) is pinned via the
    backend-independent arithmetic of scaling_impl_for."""
    from rio_tpu.ops.scaling import (
        _FUSED_MIN_ELEMS,
        scaling_core_auto,
        scaling_impl_for,
    )

    # On the CPU test mesh every shape resolves to XLA.
    assert scaling_impl_for(1 << 20, 1024) == "xla"
    # The auto path still solves correctly (it IS scaling_core here).
    cost, mass, cap = _problem(jax.random.PRNGKey(3), 64, 128)
    u, v, K, sh = scaling_core_auto(
        cost, mass, cap, eps=0.08, n_iters=15, kernel_dtype=jnp.float32
    )
    u_ref, v_ref, *_ = jax.jit(
        lambda c, a, b: __import__("rio_tpu.ops.scaling", fromlist=["scaling_core"]).scaling_core(
            c, a, b, eps=0.08, n_iters=15, kernel_dtype=jnp.float32
        )
    )(cost, mass, cap)
    np.testing.assert_allclose(np.asarray(u), np.asarray(u_ref), rtol=1e-6)
    # The selection arithmetic (what WOULD run on TPU) is shape-exact:
    # misaligned row counts and sub-VMEM problems must stay on XLA.
    assert (1 << 20) * 1024 >= _FUSED_MIN_ELEMS  # bench flagship shape qualifies
    assert (1 << 20) % 1024 == 0
    # Narrow-column exclusion (r5 TPU A/B: m=256 chained solve was 2.1x
    # SLOWER fused than XLA — 71.0 vs 33.3 ms at 1M objects): the selection
    # rule must keep sub-1024-column problems on XLA no matter how big n is.
    from rio_tpu.ops.scaling import _FUSED_MIN_COLS
    assert _FUSED_MIN_COLS >= 512
    assert (1 << 20) * 256 >= _FUSED_MIN_ELEMS  # big enough by elements...
    # ...yet excluded by column width on TPU (verified arithmetically here
    # since this suite runs on the CPU mesh).
