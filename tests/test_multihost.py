"""Multi-host bring-up helpers, single-process degradation contract.

A real multi-controller run needs N processes (impossible in this image);
what IS testable — and what the bring-up recipe relies on — is that every
helper degrades to the exact local equivalent in one process, so the same
program text runs on a laptop, one chip, and a pod.
"""

import os
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from rio_tpu.parallel import make_mesh
from rio_tpu.parallel import multihost


def test_initialize_is_noop_with_backend_already_up(monkeypatch):
    """In a long-lived single process (this test runner: conftest booted
    the backend long ago), an env-driven initialize() stays single-process
    via the RuntimeError 'before' branch instead of raising."""
    for k in (
        "JAX_COORDINATOR_ADDRESS",
        "COORDINATOR_ADDRESS",
        "TPU_WORKER_HOSTNAMES",
        "SLURM_JOB_ID",
    ):
        monkeypatch.delenv(k, raising=False)
    assert multihost.initialize() is False
    assert multihost.is_multihost() is False


def test_initialize_treats_no_cluster_valueerror_as_single_process(monkeypatch):
    """Fresh-process path: jax's cluster auto-detection raising its
    'coordinator_address should be defined' ValueError means "no cluster",
    not an error — pin the message-match against jax upgrades."""
    monkeypatch.setattr(multihost, "_already_initialized", lambda: False)

    def fake_initialize(**kw):
        raise ValueError("coordinator_address should be defined.")

    monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
    assert multihost.initialize() is False
    # Any EXPLICIT multi-process intent with the same failure is a real
    # error — a launcher passing world size but missing the coordinator
    # must not silently run as 1 of 1.
    with pytest.raises(ValueError):
        multihost.initialize("127.0.0.1:1", num_processes=2, process_id=0)
    with pytest.raises(ValueError):
        multihost.initialize(None, num_processes=2, process_id=0)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device mesh")
def test_process_rows_covers_everything_single_process():
    mesh = make_mesh(jax.devices()[:8])
    n = 64 * mesh.shape["obj"]
    rows = multihost.process_rows(n, mesh)
    # One process owns every shard.
    assert (rows.start, rows.stop) == (0, n)


@pytest.mark.slow
def test_two_process_multicontroller_solve_parity(tmp_path):
    """REAL multi-controller run: two OS processes, 2 CPU devices each,
    joined by jax.distributed over loopback (gloo — the DCN analog), one
    4-device mesh spanning both. Each process feeds only ITS object rows
    via distributed_array; the sharded hierarchical solve runs with real
    cross-process collectives; the gathered global assignment must EQUAL
    the single-process per-shard reference (the same mechanism-parity
    standard as the dryrun) and avoid the dead node."""
    import socket
    import subprocess
    import sys as _sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo = str(Path(__file__).resolve().parent.parent)
    child = str(Path(__file__).resolve().parent / "multihost_child.py")
    env = {
        # A clean env: the ambient axon sitecustomize must not leak into
        # the children (it would re-register the TPU plugin; a wedged
        # relay then hangs the solve). The child pins its own platform.
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        "HOME": os.environ.get("HOME", "/tmp"),
        "PYTHONPATH": repo,
    }
    procs = [
        subprocess.Popen(
            [_sys.executable, child, str(pid), "2", str(port), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out.decode(errors="replace"))
    finally:
        for p in procs:
            p.kill()
    assert all(p.returncode == 0 for p in procs), outs
    a = np.load(tmp_path / "assignment.npy")
    overflow, n_shards = np.load(tmp_path / "meta.npy").tolist()
    assert a.shape == (256,) and overflow == 0 and n_shards == 4
    assert not (a == 3).any(), "dead node attracted objects"
    # Mechanism parity: the cross-process solve must equal the concat of
    # per-shard local solves on identical inputs (shard-local by design).
    import jax.numpy as jnp

    from rio_tpu.parallel.hierarchical import hierarchical_assign

    key = jax.random.PRNGKey(3)
    k1, k2 = jax.random.split(key)
    obj_all = np.asarray(jax.random.normal(k1, (256, 8), jnp.float32))
    node_feat = np.asarray(jax.random.normal(k2, (8, 16), jnp.float32)) * 0.2
    cap = jnp.ones((16,), jnp.float32)
    alive = jnp.ones((16,), jnp.float32).at[3].set(0.0)
    shard = 256 // n_shards
    ref = np.concatenate(
        [
            np.asarray(
                hierarchical_assign(
                    obj_all[k * shard : (k + 1) * shard], node_feat, cap,
                    alive, n_groups=4, coarse_iters=8, fine_iters=8,
                ).assignment
            )
            for k in range(n_shards)
        ]
    )
    # EXACT equality (same numerics on the CPU children): the docs claim
    # exact mechanism parity, so the test must hold exactly that.
    np.testing.assert_array_equal(a, ref)


def test_cross_process_migration_installs_state_over_sockets(tmp_path):
    """REAL cross-process migration: two server OS processes joined only by
    sqlite membership/placement files, a client in the parent. A volatile
    counter (no persisted state) is seated on one process, migrated to the
    other via MigrateObject to the node-scoped control actor, and must
    arrive with its in-memory value intact — proving the inline
    InstallState transfer ran over real sockets between real processes."""
    import asyncio
    import socket
    import subprocess
    import sys as _sys

    ports = []
    socks = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    repo = str(Path(__file__).resolve().parent.parent)
    child = str(Path(__file__).resolve().parent / "multihost_server_child.py")
    env = {
        # Clean env: the ambient axon sitecustomize must not leak in.
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        "HOME": os.environ.get("HOME", "/tmp"),
        "PYTHONPATH": repo,
    }
    procs = [
        subprocess.Popen(
            [_sys.executable, child, str(port), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        )
        for port in ports
    ]
    addrs = [f"127.0.0.1:{p}" for p in ports]

    async def drive():
        from rio_tpu import Client
        from rio_tpu.cluster.storage.sqlite import SqliteMembershipStorage
        from rio_tpu.migration import CONTROL_TYPE, MigrateObject, MigrationAck
        from rio_tpu.object_placement.sqlite import SqliteObjectPlacement

        from .multihost_actor import Bump, Get, MhCounter, Val

        members = SqliteMembershipStorage(str(tmp_path / "members.db"))
        placement = SqliteObjectPlacement(str(tmp_path / "placement.db"))
        try:
            deadline = asyncio.get_event_loop().time() + 60.0
            while asyncio.get_event_loop().time() < deadline:
                if any(p.poll() is not None for p in procs):
                    raise AssertionError("a server child exited early")
                try:
                    active = {m.address for m in await members.active_members()}
                except Exception:
                    active = set()
                if set(addrs) <= active:
                    break
                await asyncio.sleep(0.1)
            else:
                raise TimeoutError("children never became active members")

            client = Client(members)
            try:
                out = await client.send(MhCounter, "m1", Bump(amount=7), returns=Val)
                assert out.hot == 7 and out.address in addrs
                source = out.address
                target = next(a for a in addrs if a != source)

                ack = await client.send(
                    CONTROL_TYPE,
                    source,
                    MigrateObject(
                        type_name="MhCounter", object_id="m1", target=target
                    ),
                    returns=MigrationAck,
                )
                assert ack.ok, ack.detail

                # Directory flipped in the shared sqlite placement.
                from rio_tpu.registry import ObjectId

                assert await placement.lookup(ObjectId("MhCounter", "m1")) == target

                # The next request reactivates on the target with the
                # volatile value intact — only InstallState could carry it.
                out = await client.send(MhCounter, "m1", Get(), returns=Val)
                assert out.address == target
                assert out.hot == 7
                out = await client.send(MhCounter, "m1", Bump(amount=1), returns=Val)
                assert (out.address, out.hot) == (target, 8)
            finally:
                client.close()
        finally:
            members.close()
            placement.close()

    try:
        asyncio.run(drive())
    finally:
        outs = []
        for p in procs:
            p.kill()
            out, _ = p.communicate(timeout=30)
            outs.append(out.decode(errors="replace"))
        # Surface child logs on any failure for debuggability.
        del outs


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device mesh")
def test_distributed_array_matches_device_put_and_feeds_solver():
    mesh = make_mesh(jax.devices()[:8])
    n_obj = 64 * mesh.shape["obj"]
    rows = multihost.process_rows(n_obj, mesh)
    local = np.arange(n_obj * 4, dtype=np.float32).reshape(n_obj, 4)[rows]
    arr = multihost.distributed_array(mesh, P("obj", None), local)
    assert arr.shape == (n_obj, 4)
    np.testing.assert_array_equal(np.asarray(arr), local)
    # And it is genuinely sharded input for the mesh solvers.
    from rio_tpu.parallel.hierarchical import sharded_hierarchical_assign

    d, m, g = 4, 16, 4
    node_feat = jnp.ones((d, m), jnp.float32) * 0.1
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32)
    res = sharded_hierarchical_assign(
        mesh, arr, node_feat, cap, alive, n_groups=g,
        coarse_iters=4, fine_iters=4,
    )
    a = np.asarray(res.assignment)
    assert a.shape == (n_obj,)
    assert a.min() >= 0 and a.max() < m
