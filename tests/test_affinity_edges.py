"""Affinity edge subsystem invariants: sampler, merge, tracker bound, refine.

Three layers, one contract each:

- ``EdgeSampler`` (rio_tpu/affinity): the stride gate stays unbiased, both
  the window accumulator and the folded map stay bounded under key churn,
  and the inlined hot-path gate in ``service.py`` matches ``observe``.
- ``AffinityTracker`` (jax_placement): per-object state is hard-bounded at
  ``max_objects`` even under a high-cardinality one-shot-id workload — the
  regression this PR's memory satellite pins.
- ``_affinity_refine``: the alternating linearized OT passes are
  monotonically non-increasing on the edge-cut transport cost, and the
  graph term survives cost ranges wide enough to underflow a global gauge
  shift (the per-row shift contract ``test_scaling_sinkhorn.py`` pins for
  the core, re-checked here THROUGH the refine path).
"""

import numpy as np
import pytest

from rio_tpu import ObjectId, ObjectPlacementItem
from rio_tpu.affinity import EdgeSampler, current_source, merge_edges, sending_from
from rio_tpu.object_placement.jax_placement import (
    AffinityTracker,
    JaxObjectPlacement,
)

# ---------------------------------------------------------------------------
# EdgeSampler
# ---------------------------------------------------------------------------


def test_stride_gate_is_unbiased():
    """1-in-stride sampling scaled by the stride reconstructs true totals."""
    s = EdgeSampler(stride=4, min_fold_dt=0.0)
    for _ in range(40):
        s.observe("a", "b", 100, local=False)
    assert s.sampled == 10  # tick starts at -1, so hit 1, 5, 9, ...
    s.fold(now=s._fold_t + 1.0, force=True)
    rows = s.edges()
    assert len(rows) == 1
    src, dst, bps, cps, lf = rows[0]
    assert (src, dst) == ("a", "b")
    # One 1 s window: EMA = beta * (total / dt) = 0.3 * 4000 bytes/s.
    assert bps == pytest.approx(0.3 * 40 * 100, rel=1e-6)
    assert cps == pytest.approx(0.3 * 40, rel=1e-6)
    assert lf == 0.0


def test_inlined_gate_matches_observe():
    """service.py inlines the stride gate (`_tick = (tick+1) & _mask`) and
    calls observe_sampled on the hit; drive both forms with the same
    sequence and require identical sampler state."""
    a = EdgeSampler(stride=8, min_fold_dt=0.0)
    b = EdgeSampler(stride=8, min_fold_dt=0.0)
    seq = [("x", "y", 64), ("y", "z", 256), ("x", "z", 32)] * 40
    for src, dst, nb in seq:
        a.observe(src, dst, nb, local=True)
    for src, dst, nb in seq:  # the inlined form, verbatim from service.py
        b._tick = tick = (b._tick + 1) & b._mask
        if not tick:
            b.observe_sampled(src, dst, nb, local=True)
    assert a.sampled == b.sampled > 0
    assert a._acc == b._acc


def test_self_edges_and_stride_rounding():
    s = EdgeSampler(stride=3)  # rounds up to 4
    assert s.stride == 4
    s.observe_sampled("a", "a", 1000, local=True)
    assert s.sampled == 0 and not s._acc


def test_accumulator_bounded_under_key_churn():
    """A high-cardinality storm of one-shot edges must not grow the window
    accumulator past 2x top_k between folds."""
    s = EdgeSampler(stride=1, top_k=8)
    for i in range(1000):
        s.observe(f"src{i}", "dst", 100 + i, local=False)
        assert len(s._acc) <= 16
    assert s.evictions > 0


def test_fold_keeps_hottest_topk():
    s = EdgeSampler(stride=1, top_k=4, min_fold_dt=0.0)
    for i in range(8):
        s.observe(f"s{i}", "d", (i + 1) * 1000, local=False)
    s.fold(now=s._fold_t + 1.0, force=True)
    rows = s.edges()
    assert len(rows) == 4
    assert [r[0] for r in rows] == ["s7", "s6", "s5", "s4"]  # hottest survive
    assert s.evictions == 4


def test_ema_decay_prunes_cold_edges():
    """An edge that stops sending decays geometrically and is dropped once
    both rates fall below the floor — the folded map self-cleans."""
    s = EdgeSampler(stride=1, min_fold_dt=0.0)
    s.observe("a", "b", 10_000, local=False)
    t = s._fold_t
    s.fold(now=t + 1.0, force=True)
    assert len(s._edges) == 1
    for k in range(2, 80):
        s.fold(now=t + float(k), force=True)
        if not s._edges:
            break
    assert not s._edges, "cold edge never pruned"


def test_local_frac_and_cross_bytes_split():
    s = EdgeSampler(stride=1, min_fold_dt=0.0)
    for _ in range(3):
        s.observe("a", "b", 100, local=True)
    s.observe("a", "b", 100, local=False)
    s.fold(now=s._fold_t + 1.0, force=True)
    (row,) = s.edges()
    assert row[4] == pytest.approx(0.75, abs=1e-4)  # local_frac
    # Only the non-local send counts toward the cross-node byte rate.
    assert s.cross_bytes_per_s == pytest.approx(0.3 * 100, rel=1e-6)
    g = s.gauges()
    assert g["rio.affinity.edges"] == 1.0
    assert g["rio.affinity.cross_bytes_per_s"] == pytest.approx(
        s.cross_bytes_per_s, abs=1e-3
    )
    assert set(g) == {
        "rio.affinity.edges",
        "rio.affinity.evictions",
        "rio.affinity.sampled",
        "rio.affinity.cross_bytes_per_s",
        "rio.affinity.tcp_in_bytes",
        "rio.affinity.tcp_out_bytes",
    }


def test_merge_edges_sums_and_byte_weights_local_frac():
    node_a = [["P.1", "C.1", 1000.0, 10.0, 0.0]]
    node_b = [["P.1", "C.1", 3000.0, 30.0, 1.0], ["P.2", "C.2", 50.0, 1.0, 0.5]]
    merged = merge_edges([node_a, node_b])
    assert merged[0][:2] == ["P.1", "C.1"]
    assert merged[0][2] == pytest.approx(4000.0)
    assert merged[0][3] == pytest.approx(40.0)
    assert merged[0][4] == pytest.approx(0.75)  # byte-weighted local_frac
    # Wire contract: rows may grow trailing fields; extras are ignored.
    grown = [r + ["future-field"] for r in node_b]
    assert merge_edges([node_a, grown]) == merged


def test_sending_from_nests_and_restores():
    assert current_source() == ""
    with sending_from("Stream.orders#cursor"):
        assert current_source() == "Stream.orders#cursor"
        with sending_from("Saga.s1"):
            assert current_source() == "Saga.s1"
        assert current_source() == "Stream.orders#cursor"
    assert current_source() == ""


# ---------------------------------------------------------------------------
# AffinityTracker memory bound (high-cardinality regression)
# ---------------------------------------------------------------------------


def test_affinity_tracker_high_cardinality_stays_bounded():
    """Millions of one-shot actor ids must not grow the tracker without
    limit: per-object maps are hard-capped at 2x max_objects between folds
    and at max_objects after one, with the hottest objects surviving."""
    tracker = AffinityTracker(max_objects=64)
    hot = [f"Hot.{i}" for i in range(8)]
    for i in range(2000):  # one-shot id churn with sustained hot traffic
        for k in hot:
            tracker.observe(k, "10.0.0.1:5000", weight=1.0)
        tracker.observe(f"OneShot.{i}", "10.0.0.2:5000", weight=1.0)
        assert len(tracker._obj) <= 2 * 64
    tracker.fold_rates(min_dt=0.0)
    assert len(tracker._obj) <= 64
    assert len(tracker._rates) <= 64
    assert tracker.evictions > 0
    # Eviction is coldest-first: the sustained-rate keys keep their warmth.
    assert all(k in tracker._obj for k in hot)


# ---------------------------------------------------------------------------
# _affinity_refine solver invariants
# ---------------------------------------------------------------------------

N0 = "10.0.0.1:5000"
N1 = "10.0.0.2:5000"


async def _split_pairs_placement(pairs=8, **kw):
    """Two nodes (distinct hosts), `pairs` chatty producer->consumer pairs
    seated load-balanced but pair-split: only the graph term can justify a
    move, never load-balancing luck."""
    p = JaxObjectPlacement(node_axis_size=2, mode="greedy", **kw)
    p.register_node(N0)
    p.register_node(N1)
    for i in range(pairs):
        await p.update(
            ObjectPlacementItem(ObjectId("P", str(i)), N0 if i % 2 else N1)
        )
        await p.update(
            ObjectPlacementItem(ObjectId("C", str(i)), N1 if i % 2 else N0)
        )
    return p


async def test_affinity_refine_passes_monotone_and_colocate():
    """The acceptance contract of the alternating linearized passes: the
    edge-cut transport cost is non-increasing over accepted passes, the
    run is attributed in the solve stats, and every chatty pair lands
    co-seated."""
    pairs = 8
    p = await _split_pairs_placement(
        pairs, affinity_weight=2.0, affinity_host_factor=0.0
    )
    n = p.set_edge_graph(
        [[f"P.{i}", f"C.{i}", 1000.0 + 10.0 * i, 10.0, 0.0] for i in range(pairs)]
    )
    assert n == pairs
    moved = await p.rebalance(delta=False)
    assert moved > 0
    history = list(p._affinity_history)
    accepted = [h for h in history if h["accepted"]]
    assert accepted, history
    for prev, cur in zip(accepted, accepted[1:]):
        assert cur["cut"] <= prev["cut"] + 1e-6, history
        assert cur["total"] <= prev["total"] + 1e-6, history
    # The final accepted pass fully cleared the cut for this toy graph.
    assert accepted[-1]["cut"] == pytest.approx(0.0, abs=1e-6)
    assert "+affinity" in str(p.stats.mode)
    for i in range(pairs):
        a = await p.lookup(ObjectId("P", str(i)))
        b = await p.lookup(ObjectId("C", str(i)))
        assert a == b, (i, a, b)
    # Balance survived the refine: the slack cap keeps both nodes seated.
    counts = {}
    for k, ix in p._placements.items():
        counts[ix] = counts.get(ix, 0) + 1
    assert max(counts.values()) <= pairs + 2, counts


async def test_affinity_refine_survives_wide_cost_ranges():
    """Per-row gauge shift THROUGH the graph term: a huge affinity weight
    stretches the refined cost rows far past exp-underflow range for a
    global shift (cost-range/eps >> 88). The refine must still converge,
    keep every object seated on a real node, and co-locate the pairs."""
    pairs = 6
    p = await _split_pairs_placement(
        pairs, affinity_weight=5000.0, affinity_host_factor=0.0
    )
    # Edge rates spanning 6 decades: normalization leaves weights down to
    # 1e-6, so the weighted rows mix O(5000) and O(0.005) entries.
    p.set_edge_graph(
        [
            [f"P.{i}", f"C.{i}", 10.0 ** (6 - i), 0.0, 0.0]
            for i in range(pairs)
        ]
    )
    await p.rebalance(delta=False)
    seats = set()
    for i in range(pairs):
        a = await p.lookup(ObjectId("P", str(i)))
        b = await p.lookup(ObjectId("C", str(i)))
        assert a in (N0, N1) and b in (N0, N1)
        seats.add(a)
    # The heaviest pairs must have been pulled together despite the range;
    # the featherweight tail may legally stay put (its gain is ~0).
    for i in range(3):
        a = await p.lookup(ObjectId("P", str(i)))
        b = await p.lookup(ObjectId("C", str(i)))
        assert a == b, (i, a, b)
    assert p.count() == 2 * pairs


async def test_affinity_refine_noop_without_matching_edges():
    """A graph that references no directory key (and client-source rows,
    which set_edge_graph drops) leaves the solve untouched: no history, no
    moves, no "+affinity" attribution."""
    p = await _split_pairs_placement(4, affinity_weight=2.0)
    assert p.set_edge_graph([["client", "P.0", 9e9, 10.0, 0.0]]) == 0
    p.set_edge_graph([["Ghost.a", "Ghost.b", 1000.0, 1.0, 0.0]])
    before = dict(p._placements)
    await p.rebalance(delta=False)
    assert p._placements == before
    assert not p._affinity_history
    assert "+affinity" not in str(p.stats.mode)


async def test_affinity_weight_zero_disables_refine():
    p = await _split_pairs_placement(4)  # default affinity_weight=0.0
    p.set_edge_graph([[f"P.{i}", f"C.{i}", 1000.0, 10.0, 0.0] for i in range(4)])
    await p.rebalance(delta=False)
    assert not p._affinity_history
    # Pairs stay split: without the graph term there is no reason to move.
    a = await p.lookup(ObjectId("P", "0"))
    b = await p.lookup(ObjectId("C", "0"))
    assert a != b
