"""Replicated actors: ship-on-ack, anti-affinity seats, epoch-fenced failover.

The kill-primary chaos test is the acceptance bar for the subsystem: the
primary's server dies mid-traffic with NO shutdown lifecycle, and the
promoted standby serves every subsequent request with zero lost
acknowledged writes (volatile state included — the part no state backend
covers) and zero double-activations.
"""

import asyncio

import pytest

from rio_tpu import (
    AdminCommand,
    AppData,
    LocalObjectPlacement,
    LocalStorage,
    Registry,
    ServiceObject,
    handler,
    message,
)
from rio_tpu import codec
from rio_tpu.commands import ServerInfo
from rio_tpu.migration import ReplicaAppend
from rio_tpu.object_placement import ObjectPlacementItem
from rio_tpu.registry import ObjectId, type_id
from rio_tpu.replication import ReplicationConfig, ReplicationManager
from rio_tpu.state import LocalState, StateProvider, managed_state

from .server_utils import Cluster, run_integration_test

# Module-level activation guards, reset by each test that uses them.
ACTIVATIONS: dict[str, int] = {}  # id -> lifetime LOAD count
ACTIVE: dict[str, str] = {}  # id -> address currently holding a live instance
DOUBLE: list[str] = []  # ids that activated while already active somewhere


def _reset_guards() -> None:
    ACTIVATIONS.clear()
    ACTIVE.clear()
    DOUBLE.clear()


@message
class RAdd:
    amount: int = 0


@message
class RGet:
    pass


@message
class RTotals:
    total: int = 0
    hot: int = 0
    address: str = ""


@message
class LedgerState:
    total: int = 0


class Ledger(ServiceObject):
    """Replicated stateful actor: managed ``state.total`` + volatile ``hot``.

    ``hot`` mirrors the acknowledged write count but lives only in memory;
    after a primary death it can ONLY survive through the shipped replica —
    a fresh (unreplicated) activation resets it to 0 and exposes the loss.
    """

    __replicated__ = True

    state = managed_state(LedgerState)

    def __init__(self):
        self.hot = 0

    def __migrate_state__(self):
        return {"hot": self.hot}

    def __restore_state__(self, value):
        self.hot = int(value["hot"])

    async def after_load(self, ctx: AppData) -> None:
        ACTIVATIONS[self.id] = ACTIVATIONS.get(self.id, 0) + 1
        addr = ctx.get(ServerInfo).address
        if self.id in ACTIVE:
            DOUBLE.append(self.id)
        ACTIVE[self.id] = addr

    async def before_shutdown(self, ctx: AppData) -> None:
        ACTIVE.pop(self.id, None)

    @handler
    async def add(self, msg: RAdd, ctx: AppData) -> RTotals:
        self.state.total += msg.amount
        self.hot += msg.amount
        await self.save_state(ctx)
        return RTotals(
            total=self.state.total, hot=self.hot, address=ctx.get(ServerInfo).address
        )

    @handler
    async def get(self, msg: RGet, ctx: AppData) -> RTotals:
        return RTotals(
            total=self.state.total, hot=self.hot, address=ctx.get(ServerInfo).address
        )


def build_registry() -> Registry:
    return Registry().add_type(Ledger)


async def _wait_dead(cluster: Cluster, address: str, timeout: float = 10.0) -> None:
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if not await cluster.members.is_active(address):
            return
        await asyncio.sleep(0.02)
    raise TimeoutError(f"{address} never went inactive")


def _retire_hard_killed(address: str) -> None:
    # server_exit is a HARD exit (no shutdown lifecycle): a real process
    # death takes its activations with it, but the in-process guard can't
    # see that — retire them by hand so re-seats aren't misread as doubles.
    for k, addr in list(ACTIVE.items()):
        if addr == address:
            ACTIVE.pop(k)


# ---------------------------------------------------------------------------
# Chaos: kill the primary mid-traffic → promoted standby serves with zero
# lost acknowledged writes and zero double-activations
# ---------------------------------------------------------------------------


def test_kill_primary_promoted_standby_keeps_every_acked_write():
    _reset_guards()
    state = LocalState()

    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            acked = 0
            out = await client.send(Ledger, "L1", RAdd(amount=1), returns=RTotals)
            acked += 1
            primary_addr = out.address
            for _ in range(9):
                out = await client.send(Ledger, "L1", RAdd(amount=1), returns=RTotals)
                acked += 1

            # Ship-on-ack ran before every ack: the standby row exists, the
            # seat is off-primary (anti-affinity), and the standby node
            # already holds the latest delta.
            held, epoch = await cluster.placement.standbys(ObjectId("Ledger", "L1"))
            assert held and primary_addr not in held
            standby_srv = next(
                s for s in cluster.servers if s.local_address == held[0]
            )
            assert standby_srv.replication_manager.stats.appends >= 1
            primary = next(
                s for s in cluster.servers if s.local_address == primary_addr
            )
            assert primary.replication_manager.stats.shipped >= 1

            # Primary dies hard, mid-conversation.
            primary.admin_sender().send(AdminCommand.server_exit())
            await _wait_dead(cluster, primary_addr)
            _retire_hard_killed(primary_addr)

            # Resumed traffic fails over on first touch: a survivor's
            # dead-owner branch promotes the standby through the epoch CAS,
            # the client's redirect machinery lands on it, and its first
            # activation restores the shipped replica.
            for _ in range(5):
                out = await client.send(Ledger, "L1", RAdd(amount=1), returns=RTotals)
                acked += 1
            assert out.address == held[0]

            out = await client.send(Ledger, "L1", RGet(), returns=RTotals)
            assert out.address == held[0]
            # THE guarantee: no acknowledged write lost — volatile included.
            assert (out.total, out.hot) == (acked, acked)
            assert DOUBLE == []
            assert ACTIVATIONS["L1"] == 2  # initial + exactly one failover

            promotions = sum(
                s.replication_manager.stats.promotions
                for s in cluster.servers
                if s.replication_manager is not None
            )
            assert promotions == 1
            restores = standby_srv.replication_manager.stats.replica_restores
            assert restores == 1
            # The epoch fence moved exactly once, through the CAS.
            _, epoch2 = await cluster.placement.standbys(ObjectId("Ledger", "L1"))
            assert epoch2 == epoch + 1
        finally:
            client.close()

    async def wrapped(cluster: Cluster):
        for s in cluster.servers:
            s.app_data.set(state, as_type=StateProvider)
        await body(cluster)

    asyncio.run(
        run_integration_test(
            wrapped,
            registry_builder=build_registry,
            num_servers=3,
            server_kwargs={
                "replication_config": ReplicationConfig(
                    k=1, anti_entropy_interval=0.3, seat_ttl=0.3
                )
            },
        )
    )


# ---------------------------------------------------------------------------
# Epoch fence: the standby-side append filter
# ---------------------------------------------------------------------------


def test_apply_append_fences_stale_epochs_and_local_primaries():
    async def run():
        registry = build_registry()
        mgr = ReplicationManager(
            address="127.0.0.1:1",
            registry=registry,
            placement=LocalObjectPlacement(),
            members_storage=LocalStorage(),
            app_data=AppData(),
        )

        def append(oid, epoch, seq, payload=b"p"):
            return mgr.apply_append(
                ReplicaAppend(
                    type_name="Ledger", object_id=oid, epoch=epoch, seq=seq,
                    payload=payload,
                )
            )

        ack = append("x", epoch=3, seq=1, payload=b"a")
        assert ack.ok and mgr.stats.appends == 1

        # A deposed primary (older epoch) is fenced off — and told the
        # newer epoch so it re-reads the directory.
        stale = append("x", epoch=2, seq=9)
        assert not stale.ok and stale.epoch == 3
        assert mgr.stats.append_nacks == 1
        assert mgr._replica_store[("Ledger", "x")][0] == b"a"

        # Same-epoch replays ack idempotently without regressing the store.
        replay = append("x", epoch=3, seq=1, payload=b"old")
        assert replay.ok
        assert mgr._replica_store[("Ledger", "x")][0] == b"a"

        # The post-promotion primary's newer epoch supersedes.
        newer = append("x", epoch=4, seq=1, payload=b"b")
        assert newer.ok
        assert mgr._replica_store[("Ledger", "x")][0] == b"b"

        # A node actively SERVING the object nacks appends outright: after
        # failover, late deltas from the old primary can never overwrite
        # the promoted activation.
        registry.insert("Ledger", "y", registry.new_from_type("Ledger", "y"))
        here = append("y", epoch=9, seq=1)
        assert not here.ok and "primary" in here.detail

    asyncio.run(run())


# ---------------------------------------------------------------------------
# Deposed-primary fence: the directory re-read side of the fence
# ---------------------------------------------------------------------------


def test_deposed_primary_surrenders_key_instead_of_shipping():
    """A primary that was falsely declared dead (and failed over while still
    running) must notice on its next seat-cache refresh that the directory
    names another node — and abort the ship AND the seat rewrite, rather
    than re-adopting the post-promotion epoch and passing the fence."""

    async def run():
        placement = LocalObjectPlacement()
        mgr = ReplicationManager(
            address="10.0.0.1:1",
            registry=build_registry(),
            placement=placement,
            members_storage=LocalStorage(),
            app_data=AppData(),
        )
        oid = ObjectId("Ledger", "d1")
        key = ("Ledger", "d1")
        # Post-failover directory state: another node holds the primary row.
        await placement.update(ObjectPlacementItem(oid, "10.0.0.2:2"))
        await placement.set_standbys(oid, ["10.0.0.3:3"])
        # Leftover primary-role state from before this node was deposed.
        mgr._last_shipped[key] = b"stale"
        mgr._seq[key] = 7
        mgr._dirty.add(key)

        await mgr._ship(oid, key, b"newer")

        assert mgr.stats.deposed == 1
        assert mgr.stats.shipped == 0 and mgr.stats.unreplicated == 0
        # Primary-role state surrendered — no retry, no seq to confuse a
        # later re-promotion back here.
        assert key not in mgr._last_shipped and key not in mgr._seq
        assert key not in mgr._dirty and key not in mgr._seats
        # The real primary's standby row was not rewritten.
        assert await placement.standbys(oid) == (["10.0.0.3:3"], 0)
        # Direct seat repair refuses the rewrite too (set_standbys from a
        # deposed node would clobber the promoted primary's seat choices).
        assert await mgr.repair_seats(oid) == (["10.0.0.3:3"], 0)
        assert await placement.standbys(oid) == (["10.0.0.3:3"], 0)

    asyncio.run(run())


def test_restore_replica_keeps_payload_when_hook_is_missing():
    """The shipped payload must survive an activation that cannot consume it
    (no ``__restore_state__`` yet) instead of being popped and discarded."""
    mgr = ReplicationManager(
        address="a:1",
        registry=build_registry(),
        placement=LocalObjectPlacement(),
        members_storage=LocalStorage(),
        app_data=AppData(),
    )

    class Bare:
        id = "b1"

    key = (type_id(Bare), "b1")
    payload = codec.serialize({"hot": 3})
    mgr._replica_store[key] = (payload, 5, 2)

    assert mgr.restore_replica(Bare()) is False
    assert mgr._replica_store[key] == (payload, 5, 2)  # still claimable

    # Once the hook exists, the SAME stored entry restores and is consumed.
    captured = []
    Bare.__restore_state__ = lambda self, value: captured.append(value)
    assert mgr.restore_replica(Bare()) is True
    assert captured == [{"hot": 3}]
    assert key not in mgr._replica_store
    assert mgr._seq[key] == 2  # sequence continues past the shipped delta


# ---------------------------------------------------------------------------
# Soak (nightly slow lane): sustained traffic over many replicated objects
# with a mid-run primary kill; anti-entropy repairs the seats afterwards
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_replication_soak_survives_kill_and_repairs_seats():
    _reset_guards()
    state = LocalState()
    n_objects = 8
    keys = [f"s{i}" for i in range(n_objects)]

    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            acked = dict.fromkeys(keys, 0)
            owners: dict[str, str] = {}
            for k in keys:
                out = await client.send(Ledger, k, RAdd(amount=1), returns=RTotals)
                acked[k] += 1
                owners[k] = out.address

            async def pump(rounds: int) -> None:
                for _ in range(rounds):
                    for k in keys:
                        out = await client.send(
                            Ledger, k, RAdd(amount=1), returns=RTotals
                        )
                        acked[k] += 1
                    await asyncio.sleep(0.01)

            await pump(20)

            # Kill whichever node owns the most objects.
            counts: dict[str, int] = {}
            for k in keys:
                counts[owners[k]] = counts.get(owners[k], 0) + 1
            victim_addr = max(counts, key=lambda a: counts[a])
            victim = next(
                s for s in cluster.servers if s.local_address == victim_addr
            )
            victim.admin_sender().send(AdminCommand.server_exit())
            await _wait_dead(cluster, victim_addr)
            _retire_hard_killed(victim_addr)

            await pump(20)

            survivors = {
                s.local_address
                for s in cluster.servers
                if s.local_address != victim_addr
            }
            for k in keys:
                out = await client.send(Ledger, k, RGet(), returns=RTotals)
                assert out.address in survivors
                # Zero lost acknowledged writes across the whole population.
                assert (out.total, out.hot) == (acked[k], acked[k]), k
            assert DOUBLE == []

            # Give anti-entropy a few rounds, then require every object's
            # standby set to be live, off-primary, and non-empty again —
            # seats that pointed at the victim were repaired.
            deadline = asyncio.get_event_loop().time() + 10.0
            while True:
                healthy = 0
                for k in keys:
                    oid = ObjectId("Ledger", k)
                    held, _ = await cluster.placement.standbys(oid)
                    primary = await cluster.placement.lookup(oid)
                    if (
                        held
                        and victim_addr not in held
                        and primary not in held
                        and all(h in survivors for h in held)
                    ):
                        healthy += 1
                if healthy == n_objects:
                    break
                if asyncio.get_event_loop().time() > deadline:
                    raise AssertionError(
                        f"only {healthy}/{n_objects} standby sets repaired"
                    )
                await asyncio.sleep(0.1)
        finally:
            client.close()

    async def wrapped(cluster: Cluster):
        for s in cluster.servers:
            s.app_data.set(state, as_type=StateProvider)
        await body(cluster)

    asyncio.run(
        run_integration_test(
            wrapped,
            registry_builder=build_registry,
            num_servers=3,
            timeout=60.0,
            server_kwargs={
                "replication_config": ReplicationConfig(
                    k=1, anti_entropy_interval=0.2, seat_ttl=0.2
                )
            },
        )
    )
