"""In-process RESP2 server for backend-matrix tests.

The reference tests Redis backends against a real valkey container
(``compose.yaml``, nextest setup-scripts). No Redis server exists in this
environment, so tests boot this asyncio fake instead: a real TCP server
speaking the actual wire protocol, backed by an in-memory keyspace. The
Redis backends under test use their production code path end to end
(``rio_tpu/utils/resp.py`` over a socket).

Supported commands: PING SELECT SET (incl. NX) GET DEL EXISTS INCR HSET
HGET HGETALL HDEL RPUSH LLEN LTRIM LRANGE SADD SREM SMEMBERS ZADD ZREM ZCARD
ZRANGEBYSCORE (incl. LIMIT) FLUSHDB KEYS, plus the optimistic-locking
transaction surface WATCH UNWATCH MULTI EXEC DISCARD. Watch semantics are
version-based: every write command bumps a per-key version regardless of
whether it changed the value (slightly stricter than real Redis's
modification check — over-invalidating only costs the CAS caller a retry).
"""

from __future__ import annotations

import asyncio
import fnmatch
from typing import Any

from rio_tpu.utils.resp import read_reply


def _enc_bulk(v: bytes | None) -> bytes:
    if v is None:
        return b"$-1\r\n"
    return b"$%d\r\n%s\r\n" % (len(v), v)


def _enc(v: Any) -> bytes:
    if v is None or isinstance(v, bytes):
        return _enc_bulk(v)
    if isinstance(v, bool):
        return b":%d\r\n" % int(v)
    if isinstance(v, int):
        return b":%d\r\n" % v
    if isinstance(v, str):
        return b"+%s\r\n" % v.encode()
    if isinstance(v, list):
        return b"*%d\r\n" % len(v) + b"".join(_enc(x) for x in v)
    raise TypeError(type(v))


class _Session:
    """Per-connection transaction state (lives and dies with the socket)."""

    def __init__(self) -> None:
        self.watch: dict[bytes, int] = {}  # key -> version at WATCH time
        self.multi: list[list[bytes]] | None = None  # queued cmds, if in MULTI


# Commands whose first argument is a written key; DEL/FLUSHDB handled apart.
_WRITE_CMDS = {
    "SET", "INCR", "HSET", "HDEL", "RPUSH", "LTRIM",
    "SADD", "SREM", "ZADD", "ZREM",
}


class FakeRedisServer:
    def __init__(self) -> None:
        self.data: dict[bytes, Any] = {}
        self._ver: dict[bytes, int] = {}  # key -> write version (for WATCH)
        self._server: asyncio.AbstractServer | None = None
        self._writers: set[asyncio.StreamWriter] = set()
        self.port = 0
        # Scriptable fault injection (rio_tpu.faults.FaultSchedule | None):
        # consulted before every command under ops "redis.<cmd>" (e.g.
        # "redis.get", "redis.zadd"). Injected errors surface as wire-level
        # ``-ERR injected ...`` replies; latency sleeps on the server side;
        # a hang parks the command until ``schedule.heal()`` — exactly what
        # a stalled real Redis looks like to the client pool.
        self.faults = None
        # When True, an injected error CLOSES the connection instead of
        # replying -ERR — models a crashing/restarting server, exercising
        # the client's reconnect path rather than its error path.
        self.faults_reset_conn = False

    def set_faults(self, schedule, *, reset_conn: bool = False) -> None:
        """Install (or clear, with None) the server's fault schedule."""
        self.faults = schedule
        self.faults_reset_conn = reset_conn

    async def start(self) -> "FakeRedisServer":
        self._server = await asyncio.start_server(self._serve, "127.0.0.1", 0)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # Force-close lingering client connections (pooled RedisClient
            # conns): wait_closed() would otherwise block on their handlers.
            for w in list(self._writers):
                w.close()
            await self._server.wait_closed()

    async def _serve(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._writers.add(writer)
        session = _Session()
        try:
            while True:
                try:
                    cmd = await read_reply(reader)
                except (ConnectionError, asyncio.IncompleteReadError):
                    break
                if not cmd:
                    break
                # Faults fire on standalone commands only: once a MULTI is
                # open the transaction's atomicity is the backend contract
                # (commands must reach the queue or the whole EXEC aborts),
                # so injecting a per-command -ERR there would simulate a
                # corruption no real Redis exhibits.
                if self.faults is not None and session.multi is None:
                    op = "redis." + cmd[0].decode().lower()
                    try:
                        await self.faults.perturb(op)
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:  # noqa: BLE001 — injected
                        if self.faults_reset_conn:
                            break  # close the socket: simulated crash
                        writer.write(b"-ERR injected %s\r\n" % str(e).encode())
                        await writer.drain()
                        continue
                try:
                    reply = self._handle(session, cmd)
                except Exception as e:  # noqa: BLE001 — surfaced as -ERR
                    writer.write(b"-ERR %s\r\n" % str(e).encode())
                else:
                    writer.write(reply)
                await writer.drain()
        finally:
            self._writers.discard(writer)
            writer.close()

    def _handle(self, session: _Session, cmd: list[bytes]) -> bytes:
        """Wire entry point: transaction control + MULTI queueing, then
        :meth:`_dispatch` for everything else. One call per command received
        (queued commands execute inside their EXEC)."""
        name = cmd[0].decode().upper()
        if session.multi is not None and name not in ("EXEC", "DISCARD", "MULTI", "WATCH"):
            session.multi.append(cmd)
            return b"+QUEUED\r\n"
        if name == "WATCH":
            if session.multi is not None:
                raise ValueError("WATCH inside MULTI is not allowed")
            for k in cmd[1:]:
                session.watch[k] = self._ver.get(k, 0)
            return _enc("OK")
        if name == "UNWATCH":
            session.watch.clear()
            return _enc("OK")
        if name == "MULTI":
            if session.multi is not None:
                raise ValueError("MULTI calls can not be nested")
            session.multi = []
            return _enc("OK")
        if name == "DISCARD":
            if session.multi is None:
                raise ValueError("DISCARD without MULTI")
            session.multi = None
            session.watch.clear()
            return _enc("OK")
        if name == "EXEC":
            if session.multi is None:
                raise ValueError("EXEC without MULTI")
            queued, session.multi = session.multi, None
            watched, session.watch = session.watch, {}
            if any(self._ver.get(k, 0) != v for k, v in watched.items()):
                return b"*-1\r\n"  # a watched key moved: abort, null reply
            parts = []
            for q in queued:
                try:
                    parts.append(self._dispatch(q))
                except Exception as e:  # noqa: BLE001 — -ERR in place
                    parts.append(b"-ERR %s\r\n" % str(e).encode())
            return b"*%d\r\n" % len(queued) + b"".join(parts)
        return self._dispatch(cmd)

    def _touch(self, *keys: bytes) -> None:
        for k in keys:
            self._ver[k] = self._ver.get(k, 0) + 1

    def _dispatch(self, cmd: list[bytes]) -> bytes:
        name = cmd[0].decode().upper()
        if name in _WRITE_CMDS:
            self._touch(cmd[1])
        elif name == "DEL":
            self._touch(*cmd[1:])
        elif name == "FLUSHDB":
            self._touch(*self._ver)
        return self._run_command(cmd)

    def _run_command(self, cmd: list[bytes]) -> bytes:
        name = cmd[0].decode().upper()
        args = cmd[1:]
        d = self.data
        if name == "PING":
            return _enc("PONG")
        if name in ("SELECT", "FLUSHDB"):
            if name == "FLUSHDB":
                d.clear()
            return _enc("OK")
        if name == "SET":
            opts = [a.decode().upper() for a in args[2:]]
            if "NX" in opts and args[0] in d:
                return _enc_bulk(None)
            d[args[0]] = args[1]
            return _enc("OK")
        if name == "INCR":
            v = int(d.get(args[0], b"0")) + 1
            d[args[0]] = str(v).encode()
            return _enc(v)
        if name == "GET":
            v = d.get(args[0])
            if v is not None and not isinstance(v, bytes):
                raise ValueError("WRONGTYPE")
            return _enc_bulk(v)
        if name == "DEL":
            n = sum(1 for k in args if d.pop(k, None) is not None)
            return _enc(n)
        if name == "EXISTS":
            return _enc(sum(1 for k in args if k in d))
        if name == "KEYS":
            pat = args[0].decode()
            return _enc([k for k in d if fnmatch.fnmatchcase(k.decode(), pat)])
        if name == "HSET":
            h = d.setdefault(args[0], {})
            added = 0
            for i in range(1, len(args), 2):
                added += args[i] not in h
                h[args[i]] = args[i + 1]
            return _enc(added)
        if name == "HGET":
            return _enc_bulk(d.get(args[0], {}).get(args[1]))
        if name == "HGETALL":
            out: list[bytes] = []
            for k, v in d.get(args[0], {}).items():
                out.extend((k, v))
            return _enc(out)
        if name == "HDEL":
            h = d.get(args[0], {})
            n = sum(1 for f in args[1:] if h.pop(f, None) is not None)
            if not h:
                d.pop(args[0], None)
            return _enc(n)
        if name == "RPUSH":
            lst = d.setdefault(args[0], [])
            lst.extend(args[1:])
            return _enc(len(lst))
        if name == "LTRIM":
            lst = d.get(args[0], [])
            start, stop = int(args[1]), int(args[2])
            stop = len(lst) if stop == -1 else stop + 1 if stop >= 0 else len(lst) + stop + 1
            start = max(0, start if start >= 0 else len(lst) + start)
            d[args[0]] = lst[start:stop]
            return _enc("OK")
        if name == "LLEN":
            return _enc(len(d.get(args[0], [])))
        if name == "LRANGE":
            lst = d.get(args[0], [])
            start, stop = int(args[1]), int(args[2])
            stop = len(lst) if stop == -1 else stop + 1 if stop >= 0 else len(lst) + stop + 1
            start = max(0, start if start >= 0 else len(lst) + start)
            return _enc(lst[start:stop])
        if name == "SADD":
            s = d.setdefault(args[0], set())
            n = sum(1 for m in args[1:] if m not in s)
            s.update(args[1:])
            return _enc(n)
        if name == "SREM":
            s = d.get(args[0], set())
            n = len(s & set(args[1:]))
            s -= set(args[1:])
            if not s:
                d.pop(args[0], None)
            return _enc(n)
        if name == "SMEMBERS":
            return _enc(sorted(d.get(args[0], set())))
        if name == "ZADD":
            z = d.setdefault(args[0], {})
            added = 0
            for i in range(1, len(args), 2):
                added += args[i + 1] not in z
                z[args[i + 1]] = float(args[i])
            return _enc(added)
        if name == "ZREM":
            z = d.get(args[0], {})
            n = sum(1 for m in args[1:] if z.pop(m, None) is not None)
            if not z:
                d.pop(args[0], None)
            return _enc(n)
        if name == "ZCARD":
            return _enc(len(d.get(args[0], {})))
        if name == "ZRANGEBYSCORE":
            z = d.get(args[0], {})

            def _score(raw: bytes) -> float:
                s = raw.decode()
                return float("-inf") if s == "-inf" else float("inf") if s in ("+inf", "inf") else float(s)

            lo, hi = _score(args[1]), _score(args[2])
            members = sorted(
                (m for m, sc in z.items() if lo <= sc <= hi),
                key=lambda m: (z[m], m),
            )
            if len(args) >= 6 and args[3].decode().upper() == "LIMIT":
                off, cnt = int(args[4]), int(args[5])
                members = members[off:] if cnt < 0 else members[off : off + cnt]
            return _enc(members)
        raise ValueError(f"unknown command '{name}'")
