"""In-memory fake of the OpenTelemetry SDK surfaces rio_tpu.otel imports.

The dev env ships only the ``opentelemetry`` API package (which provides
``Observation``) — not the SDK or the OTLP exporters — so without this
fake, ``otlp_sink``/``otlp_metrics_exporter`` can only be tested for their
ImportError message. :func:`install` injects ModuleType stand-ins for the
exact modules ``rio_tpu/otel.py`` imports:

* ``opentelemetry.sdk.metrics`` → :class:`FakeMeterProvider` (observable
  gauges, a ``force_flush`` that runs one collect cycle through every
  reader into its exporter)
* ``opentelemetry.sdk.metrics.export`` → ``PeriodicExportingMetricReader``
* ``opentelemetry.sdk.resources`` → ``Resource``
* ``opentelemetry.sdk.trace`` / ``....trace.export`` →
  :class:`FakeTracerProvider` + ``BatchSpanProcessor``
* ``opentelemetry.exporter.otlp.proto.grpc.{metric,trace}_exporter`` →
  in-memory exporters recording what would have gone over gRPC.

Nothing here talks to a network; exporters accumulate in plain lists the
tests assert on. Use as::

    handle = fake_otel.install()
    try:
        provider = otlp_metrics_exporter(read_gauges)
        provider.force_flush()
        assert handle.metric_exporter.exported[-1][...]
    finally:
        fake_otel.uninstall(handle)
"""

from __future__ import annotations

import sys
import types
from typing import Any, Callable

_FAKE_MODULES = (
    "opentelemetry.sdk",
    "opentelemetry.sdk.metrics",
    "opentelemetry.sdk.metrics.export",
    "opentelemetry.sdk.resources",
    "opentelemetry.sdk.trace",
    "opentelemetry.sdk.trace.export",
    "opentelemetry.exporter",
    "opentelemetry.exporter.otlp",
    "opentelemetry.exporter.otlp.proto",
    "opentelemetry.exporter.otlp.proto.grpc",
    "opentelemetry.exporter.otlp.proto.grpc.metric_exporter",
    "opentelemetry.exporter.otlp.proto.grpc.trace_exporter",
)


# ---------------------------------------------------------------------------
# Metrics side
# ---------------------------------------------------------------------------


class FakeOTLPMetricExporter:
    """Records each collect cycle as one ``{gauge_name: value}`` dict."""

    def __init__(self, endpoint: str = "") -> None:
        self.endpoint = endpoint
        self.exported: list[dict[str, float]] = []

    def export(self, snapshot: dict[str, float]) -> None:
        self.exported.append(dict(snapshot))


class PeriodicExportingMetricReader:
    def __init__(self, exporter: Any, export_interval_millis: float = 0.0) -> None:
        self.exporter = exporter
        self.export_interval_millis = export_interval_millis


class _FakeGauge:
    def __init__(self, name: str, callbacks: list[Callable]) -> None:
        self.name = name
        self.callbacks = list(callbacks)


class _FakeMeter:
    def __init__(self) -> None:
        self.gauges: dict[str, _FakeGauge] = {}

    def create_observable_gauge(
        self, name: str, callbacks: list[Callable] | None = None, **_: Any
    ) -> _FakeGauge:
        g = _FakeGauge(name, callbacks or [])
        self.gauges[name] = g
        return g


class FakeMeterProvider:
    """SDK MeterProvider stand-in with an explicit collect trigger.

    The real ``PeriodicExportingMetricReader`` collects on a timer thread;
    tests call :meth:`force_flush` (same name as the SDK method) to run one
    synchronous collect cycle: every gauge's callbacks run, their
    Observations flatten to ``{name: value}``, and each reader's exporter
    receives the snapshot.
    """

    def __init__(self, resource: Any = None, metric_readers: list | None = None) -> None:
        self.resource = resource
        self.metric_readers = list(metric_readers or [])
        self._meter = _FakeMeter()
        self.shut_down = False

    def get_meter(self, name: str, *a: Any, **k: Any) -> _FakeMeter:
        return self._meter

    def force_flush(self, timeout_millis: float = 0.0) -> bool:
        # Snapshot the gauge dict first: callbacks may register NEW gauges
        # mid-iteration (that is the auto-rescan behavior under test) and
        # those export from the next cycle, like the real SDK.
        snapshot: dict[str, float] = {}
        for gauge in list(self._meter.gauges.values()):
            for cb in gauge.callbacks:
                for obs in cb(None) or []:
                    snapshot[gauge.name] = obs.value
        for reader in self.metric_readers:
            reader.exporter.export(snapshot)
        return True

    def shutdown(self, timeout_millis: float = 0.0) -> None:
        self.shut_down = True


# ---------------------------------------------------------------------------
# Trace side
# ---------------------------------------------------------------------------


class FakeSpan:
    def __init__(self, name: str, start_time: int | None = None) -> None:
        self.name = name
        self.start_time = start_time
        self.end_time: int | None = None
        self.attributes: dict[str, Any] = {}

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def end(self, end_time: int | None = None) -> None:
        self.end_time = end_time


class _FakeTracer:
    def __init__(self, finished: list[FakeSpan]) -> None:
        self._finished = finished

    def start_span(self, name: str, start_time: int | None = None, **_: Any) -> FakeSpan:
        span = FakeSpan(name, start_time)
        self._finished.append(span)
        return span


class FakeOTLPSpanExporter:
    def __init__(self, endpoint: str = "") -> None:
        self.endpoint = endpoint


class BatchSpanProcessor:
    def __init__(self, exporter: Any) -> None:
        self.exporter = exporter


class FakeTracerProvider:
    def __init__(self, resource: Any = None) -> None:
        self.resource = resource
        self.processors: list[Any] = []
        self.finished_spans: list[FakeSpan] = []

    def add_span_processor(self, processor: Any) -> None:
        self.processors.append(processor)

    def get_tracer(self, name: str, *a: Any, **k: Any) -> _FakeTracer:
        return _FakeTracer(self.finished_spans)


class Resource:
    def __init__(self, attributes: dict[str, Any]) -> None:
        self.attributes = dict(attributes)

    @classmethod
    def create(cls, attributes: dict[str, Any] | None = None) -> "Resource":
        return cls(attributes or {})


# ---------------------------------------------------------------------------
# sys.modules injection
# ---------------------------------------------------------------------------


class Handle:
    """What :func:`install` returns: the classes tests assert against plus
    the pre-existing sys.modules entries to restore on uninstall."""

    def __init__(self, saved: dict[str, Any]) -> None:
        self.saved = saved
        # The most recent instances, captured by the instrumented ctors.
        self.meter_providers: list[FakeMeterProvider] = []
        self.tracer_providers: list[FakeTracerProvider] = []
        self.metric_exporters: list[FakeOTLPMetricExporter] = []


def install() -> Handle:
    """Inject the fake SDK modules into ``sys.modules``; returns a Handle."""
    saved = {name: sys.modules.get(name) for name in _FAKE_MODULES}
    handle = Handle(saved)

    def _tracked(cls: type, bucket: list) -> type:
        class Tracked(cls):  # type: ignore[valid-type,misc]
            def __init__(self, *a: Any, **k: Any) -> None:
                super().__init__(*a, **k)
                bucket.append(self)

        Tracked.__name__ = cls.__name__
        Tracked.__qualname__ = cls.__qualname__
        return Tracked

    meter_provider_cls = _tracked(FakeMeterProvider, handle.meter_providers)
    tracer_provider_cls = _tracked(FakeTracerProvider, handle.tracer_providers)
    metric_exporter_cls = _tracked(FakeOTLPMetricExporter, handle.metric_exporters)

    def _mod(name: str, **attrs: Any) -> types.ModuleType:
        mod = types.ModuleType(name)
        for key, value in attrs.items():
            setattr(mod, key, value)
        sys.modules[name] = mod
        return mod

    _mod("opentelemetry.sdk")
    _mod("opentelemetry.sdk.metrics", MeterProvider=meter_provider_cls)
    _mod(
        "opentelemetry.sdk.metrics.export",
        PeriodicExportingMetricReader=PeriodicExportingMetricReader,
    )
    _mod("opentelemetry.sdk.resources", Resource=Resource)
    _mod("opentelemetry.sdk.trace", TracerProvider=tracer_provider_cls)
    _mod("opentelemetry.sdk.trace.export", BatchSpanProcessor=BatchSpanProcessor)
    _mod("opentelemetry.exporter")
    _mod("opentelemetry.exporter.otlp")
    _mod("opentelemetry.exporter.otlp.proto")
    _mod("opentelemetry.exporter.otlp.proto.grpc")
    _mod(
        "opentelemetry.exporter.otlp.proto.grpc.metric_exporter",
        OTLPMetricExporter=metric_exporter_cls,
    )
    _mod(
        "opentelemetry.exporter.otlp.proto.grpc.trace_exporter",
        OTLPSpanExporter=FakeOTLPSpanExporter,
    )
    return handle


def uninstall(handle: Handle) -> None:
    """Restore ``sys.modules`` exactly as :func:`install` found it."""
    for name, before in handle.saved.items():
        if before is None:
            sys.modules.pop(name, None)
        else:
            sys.modules[name] = before
