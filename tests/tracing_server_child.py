"""Child server process for the cross-process trace-propagation test.

Boots ONE real rio-tpu server on the given port, sqlite-joined to its
sibling, with metrics on (the default) so the parent can DUMP_STATS each
node's exemplar trace ids over the wire. Run with a clean env
(PYTHONPATH=<repo> only) — the ambient axon sitecustomize must not leak in.
"""

import asyncio
import os
import sys

port, dbdir = sys.argv[1], sys.argv[2]
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rio_tpu import Server  # noqa: E402
from rio_tpu.cluster.membership_protocol import LocalClusterProvider  # noqa: E402
from rio_tpu.cluster.storage.sqlite import SqliteMembershipStorage  # noqa: E402
from rio_tpu.object_placement.sqlite import SqliteObjectPlacement  # noqa: E402
from tests.tracing_actor import build_registry  # noqa: E402


async def main() -> None:
    members = SqliteMembershipStorage(os.path.join(dbdir, "members.db"))
    placement = SqliteObjectPlacement(os.path.join(dbdir, "placement.db"))
    server = Server(
        address=f"127.0.0.1:{port}",
        registry=build_registry(),
        cluster_provider=LocalClusterProvider(members),
        object_placement_provider=placement,
    )
    await server.prepare()
    await server.bind()
    print("READY", flush=True)
    await server.run()


asyncio.run(main())
