"""rio_tpu.utils.loop: graceful degradation without uvloop installed."""

from __future__ import annotations

import asyncio

from rio_tpu.utils.loop import install_uvloop, loop_flavor


def test_install_uvloop_graceful_without_uvloop():
    # The CI image has no uvloop: install must return False (not raise)
    # and leave the stock policy working.
    try:
        import uvloop  # noqa: F401

        have_uvloop = True
    except ImportError:
        have_uvloop = False

    installed = install_uvloop()
    assert installed == have_uvloop
    assert loop_flavor() == ("uvloop" if have_uvloop else "asyncio")
    # The policy still produces a usable loop either way.
    assert asyncio.run(_probe()) == 42


async def _probe() -> int:
    await asyncio.sleep(0)
    return 42
