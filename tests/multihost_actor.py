"""Shared actor for the cross-process migration test.

Imported by BOTH sides of the real-socket run: the server child process
(``multihost_server_child.py``) registers it; the parent test imports it so
the ``@message`` decorators register the same wire names for the client's
codec. Keep it dependency-light — the child boots with a clean env.
"""

from rio_tpu import AppData, Registry, ServerInfo, ServiceObject, handler, message


@message(name="mh.Bump")
class Bump:
    amount: int = 0


@message(name="mh.Get")
class Get:
    pass


@message(name="mh.Val")
class Val:
    hot: int = 0
    address: str = ""


class MhCounter(ServiceObject):
    """Volatile-state-only counter: ``hot`` lives purely in memory, so it
    survives a migration ONLY if the inline InstallState transfer really
    carried it — a fresh activation on the target would reset it to 0."""

    def __init__(self):
        self.hot = 0

    def __migrate_state__(self):
        return {"hot": self.hot}

    def __restore_state__(self, value):
        self.hot = int(value["hot"])

    @handler
    async def bump(self, msg: Bump, ctx: AppData) -> Val:
        self.hot += msg.amount
        return Val(hot=self.hot, address=ctx.get(ServerInfo).address)

    @handler
    async def get(self, msg: Get, ctx: AppData) -> Val:
        return Val(hot=self.hot, address=ctx.get(ServerInfo).address)


def build_registry() -> Registry:
    return Registry().add_type(MhCounter)
