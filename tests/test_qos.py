"""Request QoS: scheduler semantics, deadline propagation, live dispatch.

Three layers under test:

* **Scheduler units (fake clock)** — token-bucket admission sheds, bounded
  queue sheds, strict priority tiers over the weighted-fair ring, stride
  fairness ratios, deadline drops while parked (handler never runs), and
  the uniform-traffic fast path.
* **Scope helpers** — the contextvar request scope internal hops read to
  decrement-and-forward the remaining budget.
* **Live clusters** — a budgeted request crossing the redirect-follow path,
  an actor→actor internal hop, and the readscale stale-standby proxy hop
  arrives with a strictly smaller budget each time; an already-expired
  inbound is answered DEADLINE_EXCEEDED *without the handler running*.
"""

import asyncio
import time

import pytest

from rio_tpu import (
    AppData,
    Registry,
    ServiceObject,
    handler,
    message,
    readonly,
)
from rio_tpu.commands import ServerInfo
from rio_tpu.errors import DeadlineExceeded
from rio_tpu.protocol import ErrorKind, RequestEnvelope
from rio_tpu.qos import (
    FAIR_CLASS,
    QosConfig,
    QosScheduler,
    class_of,
    current_scope,
    detach_scope,
    remaining_budget_ms,
    request_scope,
    scope_budget_ms,
)
from rio_tpu.registry import ObjectId, type_id
from rio_tpu.replication import ReplicationConfig

from .server_utils import Cluster, run_integration_test

# ---------------------------------------------------------------------------
# Fake clock
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _env(tenant: str = "", priority: int = 0, deadline_ms: int = 0) -> RequestEnvelope:
    return RequestEnvelope(
        "Svc", "o1", "Msg", b"", tenant=tenant, priority=priority,
        deadline_ms=deadline_ms,
    )


# ---------------------------------------------------------------------------
# Pure helpers
# ---------------------------------------------------------------------------


def test_class_of():
    assert class_of(0) == FAIR_CLASS
    assert class_of(1) == "p1"
    assert class_of(7) == "p7"


def test_remaining_budget_ms_decrements_and_never_invents():
    assert remaining_budget_ms(0, 10.0) == 0  # no deadline stays no deadline
    assert remaining_budget_ms(1000, 0.25) == 750
    assert remaining_budget_ms(1000, 1.0) == 0  # exactly spent
    assert remaining_budget_ms(1000, 5.0) == 0  # long spent — never negative
    assert remaining_budget_ms(1000, 0.0) == 1000


def test_scope_helpers_default_install_and_detach():
    assert current_scope() == ("", 0, 0.0)
    assert scope_budget_ms() == 0  # no deadline in scope
    now = time.monotonic()
    with request_scope("bulk", 2, now + 1.5):
        assert current_scope() == ("bulk", 2, now + 1.5)
        b = scope_budget_ms(now=now)
        assert b == 1500
        assert scope_budget_ms(now=now + 2.0) == -1  # spent, not 0
        # Nested scopes restore on exit.
        with request_scope("", 0, 0.0):
            assert scope_budget_ms() == 0
        assert current_scope()[0] == "bulk"
    assert current_scope() == ("", 0, 0.0)


def test_scope_budget_floors_at_one_ms_while_unexpired():
    now = time.monotonic()
    with request_scope("t", 0, now + 0.0004):
        # 0.4 ms left: genuinely unexpired must forward >= 1, never 0
        # (0 would mean "no deadline" downstream).
        assert scope_budget_ms(now=now) == 1


def test_detach_scope_clears_inherited_scope():
    async def body():
        with request_scope("bulk", 1, time.monotonic() + 5.0):

            async def background():
                detach_scope()
                return current_scope()

            # Tasks copy the context at creation: without detach they would
            # carry this one request's deadline forever.
            return await asyncio.create_task(background())

    assert asyncio.run(body()) == ("", 0, 0.0)


# ---------------------------------------------------------------------------
# Admission: token bucket + bounded queues (fake clock, no loop needed)
# ---------------------------------------------------------------------------


def test_token_bucket_sheds_and_refills():
    clk = FakeClock()
    sched = QosScheduler(
        QosConfig(tenant_rates={"bulk": (10.0, 3.0)}), clock=clk
    )
    # Burst of 3 admitted, 4th shed.
    for _ in range(3):
        assert sched.admit(_env(tenant="bulk")) is None
    err = sched.admit(_env(tenant="bulk"))
    assert err is not None and err.kind == ErrorKind.SERVER_BUSY
    assert "qos:" in err.detail
    assert sched.stats.sheds == 1 and sched.stats.admitted == 3
    # Other tenants are unaffected (no default rate configured).
    assert sched.admit(_env(tenant="frontend")) is None
    # 10 tokens/s: 0.1 s buys exactly one more admit.
    clk.advance(0.1)
    assert sched.admit(_env(tenant="bulk")) is None
    assert sched.admit(_env(tenant="bulk")) is not None


def test_interactive_shed_counted_separately():
    clk = FakeClock()
    sched = QosScheduler(
        QosConfig(tenant_rates={"vip": (1.0, 1.0)}), clock=clk
    )
    assert sched.admit(_env(tenant="vip", priority=2)) is None
    assert sched.admit(_env(tenant="vip", priority=2)) is not None
    assert sched.stats.interactive_sheds == 1
    assert sched.stats.interactive_admitted == 1
    # RED row keyed (tenant, class) recorded the shed.
    rows = {(r[0], r[1]): r for r in sched.tenant_rows()}
    assert rows[("vip", "p2")][6] == 1


def test_admit_stamps_monotonic_deadline():
    clk = FakeClock(2000.0)
    sched = QosScheduler(clock=clk)
    env = _env(deadline_ms=500)
    assert sched.admit(env) is None
    assert env._qos_deadline == pytest.approx(2000.5)
    env2 = _env()
    assert sched.admit(env2) is None
    # Unclassified requests ride the fast path: no stamp at all.
    assert getattr(env2, "_qos_deadline", 0.0) == 0.0


def test_queue_full_sheds_server_busy():
    clk = FakeClock()
    sched = QosScheduler(QosConfig(max_concurrent=1, max_queue=2), clock=clk)

    async def body():
        release = asyncio.Event()

        async def blocker(env):
            await release.wait()
            from rio_tpu.protocol import ResponseEnvelope

            return ResponseEnvelope.ok(b"")

        holder = _env(tenant="t")
        assert sched.admit(holder) is None
        hold_task = asyncio.create_task(sched.run(blocker, holder))
        await asyncio.sleep(0)
        assert sched.running == 1
        # Two park in tenant t's fair queue (max_queue=2)...
        parked = []
        for _ in range(2):
            e = _env(tenant="t")
            assert sched.admit(e) is None
            parked.append(asyncio.create_task(sched.run(blocker, e)))
        await asyncio.sleep(0)
        assert sched.queued == 2
        assert sched.queue_depths() == {FAIR_CLASS: 2}
        # ...the third is shed at the door.
        err = sched.admit(_env(tenant="t"))
        assert err is not None and err.kind == ErrorKind.SERVER_BUSY
        assert "queue full" in err.detail
        release.set()
        await asyncio.gather(hold_task, *parked)
        assert sched.running == 0 and sched.queued == 0

    asyncio.run(body())


# ---------------------------------------------------------------------------
# Dispatch order: strict tiers, weighted fairness, deadline drops
# ---------------------------------------------------------------------------


async def _drain_order(sched: QosScheduler, envs: list[RequestEnvelope]):
    """Park ``envs`` behind a held slot, release, return handler-start
    order as (tenant, priority) pairs."""
    from rio_tpu.protocol import ResponseEnvelope

    order: list[tuple[str, int]] = []
    release = asyncio.Event()

    async def blocker(env):
        await release.wait()
        return ResponseEnvelope.ok(b"")

    async def record(env):
        order.append((env.tenant, env.priority))
        return ResponseEnvelope.ok(b"")

    holder = _env(tenant="holder")
    assert sched.admit(holder) is None
    hold_task = asyncio.create_task(sched.run(blocker, holder))
    await asyncio.sleep(0)
    tasks = []
    for e in envs:
        assert sched.admit(e) is None
        tasks.append(asyncio.create_task(sched.run(record, e)))
        await asyncio.sleep(0)  # deterministic enqueue order
    release.set()
    results = await asyncio.gather(hold_task, *tasks)
    return order, results[1:]


def test_strict_priority_tiers_dispatch_before_fair_ring():
    sched = QosScheduler(QosConfig(max_concurrent=1))

    async def body():
        envs = [
            _env(tenant="bulk"),
            _env(tenant="vip", priority=1),
            _env(tenant="bulk"),
            _env(tenant="vip", priority=3),
            _env(tenant="vip", priority=2),
        ]
        order, _ = await _drain_order(sched, envs)
        # Tiers drain highest-first regardless of arrival; fair ring last.
        assert [p for _, p in order] == [3, 2, 1, 0, 0]

    asyncio.run(body())


def test_weighted_fair_ring_respects_tenant_weights():
    sched = QosScheduler(
        QosConfig(max_concurrent=1, tenant_weights={"a": 3.0, "b": 1.0})
    )

    async def body():
        envs = []
        for _ in range(9):
            envs.append(_env(tenant="a"))
        for _ in range(3):
            envs.append(_env(tenant="b"))
        order, _ = await _drain_order(sched, envs)
        # Stride scheduling: in any first-8 window tenant a gets ~3x the
        # starts of b, and b is never starved out of the window entirely.
        first8 = [t for t, _ in order[:8]]
        assert first8.count("a") >= 5
        assert first8.count("b") >= 1
        # Everyone eventually runs.
        assert len(order) == 12
        assert [t for t, _ in order].count("b") == 3

    asyncio.run(body())


def test_idle_tenant_rearrival_does_not_bank_vtime():
    sched = QosScheduler(QosConfig(max_concurrent=1))

    async def body():
        # Round 1: tenants x and y trade grants, advancing the ring clock.
        envs = [_env(tenant="x"), _env(tenant="y")] * 3
        await _drain_order(sched, envs)
        # Round 2: z arrives for the first time (vtime 0). The re-arrival
        # clamp seats it at the CURRENT ring clock, so it cannot monopolize
        # grants against x's banked backlog.
        envs2 = [_env(tenant="z") for _ in range(4)] + [
            _env(tenant="x") for _ in range(4)
        ]
        order, _ = await _drain_order(sched, envs2)
        first4 = [t for t, _ in order[:4]]
        assert "x" in first4  # z did not run 4-in-a-row off banked credit

    asyncio.run(body())


def test_deadline_expires_while_parked_handler_never_runs():
    clk = FakeClock()
    sched = QosScheduler(QosConfig(max_concurrent=1), clock=clk)

    async def body():
        from rio_tpu.protocol import ResponseEnvelope

        release = asyncio.Event()
        ran: list[str] = []

        async def blocker(env):
            await release.wait()
            return ResponseEnvelope.ok(b"")

        async def never(env):
            ran.append(env.tenant)
            return ResponseEnvelope.ok(b"")

        # Classified holder: unclassified requests ride the zero-wrapper
        # fast path and never occupy a slot.
        holder = _env(tenant="h")
        assert sched.admit(holder) is None
        hold = asyncio.create_task(sched.run(blocker, holder))
        await asyncio.sleep(0)
        doomed = _env(tenant="t", deadline_ms=100)
        assert sched.admit(doomed) is None
        doomed_task = asyncio.create_task(sched.run(never, doomed))
        await asyncio.sleep(0)
        assert sched.queued == 1
        # Budget expires while parked; the grant resolves to the error.
        clk.advance(0.2)
        release.set()
        resp = (await asyncio.gather(hold, doomed_task))[1]
        assert resp.error is not None
        assert resp.error.kind == ErrorKind.DEADLINE_EXCEEDED
        assert ran == []  # the doomed handler never started
        assert sched.stats.deadline_drops == 1
        rows = {(r[0], r[1]): r for r in sched.tenant_rows()}
        assert rows[("t", FAIR_CLASS)][7] == 1

    asyncio.run(body())


def test_already_expired_inbound_dropped_before_queuing():
    clk = FakeClock()
    sched = QosScheduler(clock=clk)

    async def body():
        ran: list[int] = []

        async def never(env):
            ran.append(1)

        env = _env(deadline_ms=50)
        assert sched.admit(env) is None
        clk.advance(0.1)  # budget spent between decode and dispatch
        resp = await sched.run(never, env)
        assert resp.error is not None
        assert resp.error.kind == ErrorKind.DEADLINE_EXCEEDED
        assert ran == []
        assert sched.stats.deadline_drops == 1

    asyncio.run(body())


def test_fast_path_grants_without_queuing_and_installs_scope():
    clk = FakeClock(500.0)
    sched = QosScheduler(clock=clk)

    async def body():
        from rio_tpu.protocol import ResponseEnvelope

        seen: list[tuple] = []

        async def probe(env):
            seen.append(current_scope())
            return ResponseEnvelope.ok(b"x")

        env = _env(tenant="frontend", priority=2, deadline_ms=1000)
        assert sched.admit(env) is None
        resp = await sched.run(probe, env)
        assert resp.is_ok
        # Scope carried tenant/priority and the stamped monotonic expiry.
        assert seen == [("frontend", 2, pytest.approx(501.0))]
        # Scope is reset after the handler returns.
        assert current_scope() == ("", 0, 0.0)
        assert sched.running == 0 and sched.queued == 0
        rows = {(r[0], r[1]): r for r in sched.tenant_rows()}
        assert rows[("frontend", "p2")][2] == 1

    asyncio.run(body())


def test_handler_error_counts_in_red_row():
    sched = QosScheduler()

    async def body():
        from rio_tpu.protocol import ResponseEnvelope, ResponseError

        async def fails(env):
            return ResponseEnvelope.err(ResponseError.server_busy("boom"))

        env = _env(tenant="t")
        assert sched.admit(env) is None
        await sched.run(fails, env)
        rows = {(r[0], r[1]): r for r in sched.tenant_rows()}
        assert rows[("t", FAIR_CLASS)][3] == 1  # errors

    asyncio.run(body())


def test_gauges_shape():
    sched = QosScheduler()
    g = sched.gauges()
    for key in (
        "rio.qos.running",
        "rio.qos.queued",
        "rio.qos.admitted",
        "rio.qos.sheds",
        "rio.qos.deadline_drops",
        "rio.qos.interactive_admitted",
        "rio.qos.interactive_sheds",
    ):
        assert g[key] == 0.0


# ---------------------------------------------------------------------------
# Live cluster: end-to-end classification + deadline propagation
# ---------------------------------------------------------------------------


@message
class Probe:
    sleep_s: float = 0.0


@message
class ProbeOut:
    tenant: str = ""
    priority: int = 0
    budget_ms: int = 0
    address: str = ""


@message
class HopProbe:
    target_id: str = ""
    sleep_s: float = 0.0


class ScopeReporter(ServiceObject):
    """Handlers cannot see envelopes — but the QoS request scope IS
    visible, which is exactly the propagation contract under test."""

    @handler
    async def probe(self, msg: Probe, ctx: AppData) -> ProbeOut:
        # Budget at handler START — the scheduler's grant-time contract.
        # (Read before the sleep: a handler's own execution may legally
        # outlive the deadline; only starting already-spent is a bug.)
        budget = scope_budget_ms()
        if msg.sleep_s:
            await asyncio.sleep(msg.sleep_s)
        tenant, priority, _ = current_scope()
        return ProbeOut(
            tenant=tenant,
            priority=priority,
            budget_ms=budget,
            address=ctx.get(ServerInfo).address,
        )

    @handler
    async def hop(self, msg: HopProbe, ctx: AppData) -> ProbeOut:
        # Burn measurable budget, then hop: the next actor must observe a
        # STRICTLY smaller remaining budget than this request arrived with.
        # A spent budget is refused AT the hop (the target never runs) —
        # surfaced here as a HandlerError, reported via a marker ProbeOut.
        if msg.sleep_s:
            await asyncio.sleep(msg.sleep_s)
        from rio_tpu.errors import HandlerError

        try:
            return await ServiceObject.send(
                ctx, ScopeReporter, msg.target_id, Probe(), returns=ProbeOut
            )
        except HandlerError as e:
            return ProbeOut(tenant="refused", budget_ms=-1, address=str(e))


def build_qos_registry() -> Registry:
    return Registry().add_type(ScopeReporter)


def test_client_to_server_classification_and_budget_arrival():
    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            out = await client.send(
                ScopeReporter, "s1", Probe(), returns=ProbeOut,
                tenant="frontend", priority=2, deadline_ms=5000,
            )
            assert out.tenant == "frontend" and out.priority == 2
            # The handler sees remaining budget: positive, never more than
            # the client sent (time only ever drains it).
            assert 0 < out.budget_ms <= 5000
            # Unclassified request: empty scope, no deadline.
            out = await client.send(
                ScopeReporter, "s1", Probe(), returns=ProbeOut
            )
            assert (out.tenant, out.priority, out.budget_ms) == ("", 0, 0)
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_qos_registry,
            num_servers=2,
            server_kwargs={"qos_config": QosConfig()},
        )
    )


def test_internal_hop_arrives_with_strictly_smaller_budget():
    # One server: ServiceObject.send does not follow redirects (remote
    # owners surface as errors by design) — the hop under test is the
    # internal-queue one, not cross-node routing.
    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            # Seat both actors first so the hop measures propagation, not
            # placement latency.
            await client.send(ScopeReporter, "a", Probe(), returns=ProbeOut)
            await client.send(ScopeReporter, "b", Probe(), returns=ProbeOut)
            out = await client.send(
                ScopeReporter, "a", HopProbe(target_id="b", sleep_s=0.05),
                returns=ProbeOut, tenant="frontend", deadline_ms=5000,
            )
            # The 50 ms burned before the hop is visible downstream.
            assert 0 < out.budget_ms <= 5000 - 50
            assert out.tenant == "frontend"  # classification propagated
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_qos_registry,
            num_servers=1,
            server_kwargs={"qos_config": QosConfig()},
        )
    )


def test_internal_hop_refuses_spent_budget_before_handler():
    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            await client.send(ScopeReporter, "a", Probe(), returns=ProbeOut)
            await client.send(ScopeReporter, "b", Probe(), returns=ProbeOut)
            # 80 ms budget, 200 ms burned before the hop: the hop is
            # refused at the internal dispatch point — actor b's handler
            # never runs, actor a sees the DEADLINE_EXCEEDED refusal.
            out = await client.send(
                ScopeReporter, "a", HopProbe(target_id="b", sleep_s=0.2),
                returns=ProbeOut, deadline_ms=80,
            )
            assert out.tenant == "refused" and out.budget_ms == -1
            assert "DEADLINE_EXCEEDED" in out.address
            assert "budget spent before internal dispatch" in out.address
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_qos_registry,
            num_servers=1,
            server_kwargs={"qos_config": QosConfig()},
        )
    )


def test_redirect_follow_decrements_budget():
    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            seated = await client.send(
                ScopeReporter, "r1", Probe(), returns=ProbeOut
            )
            wrong = next(
                a for a in cluster.addresses if a != seated.address
            )
            # Poison the placement cache: the next attempt dials the wrong
            # node, eats a Redirect, and the retry RE-ENCODES the envelope
            # with the remaining budget (protocol.py re-encode contract).
            client._placement.put((type_id(ScopeReporter), "r1"), wrong)
            rd0 = client.stats.redirects
            out = await client.send(
                ScopeReporter, "r1", Probe(), returns=ProbeOut,
                deadline_ms=5000,
            )
            assert client.stats.redirects == rd0 + 1  # the hop happened
            assert out.address == seated.address
            assert 0 < out.budget_ms < 5000  # drained, never invented
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_qos_registry,
            num_servers=2,
            server_kwargs={"qos_config": QosConfig()},
        )
    )


def test_expired_inbound_is_dropped_without_running_handler():
    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            # Hold the single slot with a slow request, then send a
            # short-deadline one: it parks, expires, and is answered
            # DEADLINE_EXCEEDED without the handler observing it.
            seated = await client.send(
                ScopeReporter, "d1", Probe(), returns=ProbeOut
            )
            server = next(
                s for s in cluster.servers
                if s.local_address == seated.address
            )
            # The holder is classified (tenant set) so it occupies the
            # single slot — unclassified traffic bypasses slot accounting.
            slow = asyncio.create_task(
                client.send(
                    ScopeReporter, "d1", Probe(sleep_s=0.6), returns=ProbeOut,
                    tenant="holder",
                )
            )
            await asyncio.sleep(0.1)
            with pytest.raises(DeadlineExceeded):
                await client.send(
                    ScopeReporter, "d1", Probe(), returns=ProbeOut,
                    deadline_ms=120,
                )
            # The server DROPPED the parked request (deadline_drops moved):
            # its handler never ran — the only handler execution was the
            # slow holder's.
            assert server.qos.stats.deadline_drops >= 1
            assert client.stats.deadline_exceeded >= 1
            await slow
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_qos_registry,
            num_servers=1,
            server_kwargs={"qos_config": QosConfig(max_concurrent=1)},
        )
    )


def test_token_bucket_shed_surfaces_as_retryable_busy_and_counts():
    from rio_tpu import codec
    from rio_tpu.client import _ServerConns
    from rio_tpu.errors import RetryExhausted
    from rio_tpu.protocol import decode_response, encode_request_frame
    from rio_tpu.utils.backoff import ExponentialBackoff

    async def raw_probe(address: str, tenant: str):
        """One framed request with no client retry middleware: the shed
        response itself is the thing under test."""
        pool = _ServerConns(address, 1, 2.0)
        try:
            req = RequestEnvelope(
                type_id(ScopeReporter), "t1", type_id(Probe),
                codec.serialize(Probe()), tenant=tenant,
            )
            conn = await pool.acquire()
            try:
                raw = await conn.roundtrip(encode_request_frame(req))
            finally:
                pool.release(conn, reuse=True)
            return decode_response(raw)
        finally:
            pool.close()

    async def body(cluster: Cluster):
        address = cluster.addresses[0]
        server = cluster.servers[0]
        # Burst of 2 admitted, then the bucket is dry: retryable
        # SERVER_BUSY with the "qos:" marker the client stats key on.
        shed = None
        for _ in range(4):
            resp = await raw_probe(address, "bulk")
            if resp.error is not None:
                shed = resp.error
        assert shed is not None
        assert shed.kind == ErrorKind.SERVER_BUSY
        assert shed.detail.startswith("qos:")
        assert server.qos.stats.sheds >= 1
        # And through the real client: the shed is counted in
        # ClientStats.qos_sheds (distinct from generic busy_retries).
        client = cluster.client(
            backoff=ExponentialBackoff(initial=1e-3, max_retries=2)
        )
        try:
            for _ in range(4):
                try:
                    await client.send(
                        ScopeReporter, "t1", Probe(), returns=ProbeOut,
                        tenant="bulk",
                    )
                except RetryExhausted:
                    pass
            assert client.stats.qos_sheds >= 1
            assert client.stats.busy_retries >= client.stats.qos_sheds
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_qos_registry,
            num_servers=1,
            server_kwargs={
                "qos_config": QosConfig(tenant_rates={"bulk": (1.0, 2.0)})
            },
        )
    )


# ---------------------------------------------------------------------------
# Readscale proxy hop: stale standby forwards with a decremented budget
# ---------------------------------------------------------------------------


@message
class RBump:
    amount: int = 1


@message
class RProbe:
    pass


class ReplicatedReporter(ServiceObject):
    __replicated__ = True

    def __init__(self):
        self.version = 0

    def __migrate_state__(self):
        return {"version": self.version}

    def __restore_state__(self, value):
        self.version = int(value["version"])

    @handler
    async def bump(self, msg: RBump, ctx: AppData) -> ProbeOut:
        self.version += msg.amount
        return ProbeOut(address=ctx.get(ServerInfo).address)

    @readonly
    @handler
    async def read(self, msg: RProbe, ctx: AppData) -> ProbeOut:
        tenant, priority, _ = current_scope()
        return ProbeOut(
            tenant=tenant,
            priority=priority,
            budget_ms=scope_budget_ms(),
            address=ctx.get(ServerInfo).address,
        )


RTNAME = type_id(ReplicatedReporter)


def build_replicated_registry() -> Registry:
    return Registry().add_type(ReplicatedReporter)


def test_readscale_proxy_hop_forwards_decremented_budget():
    from rio_tpu import ReadScaleConfig
    from rio_tpu.client import _ServerConns
    from rio_tpu import codec
    from rio_tpu.protocol import decode_response, encode_request_frame

    async def raw_read(address: str, deadline_ms: int) -> ProbeOut:
        pool = _ServerConns(address, 1, 2.0)
        try:
            req = RequestEnvelope(
                RTNAME, "p1", type_id(RProbe), codec.serialize(RProbe()),
                tenant="reader", deadline_ms=deadline_ms,
            )
            conn = await pool.acquire()
            try:
                raw = await conn.roundtrip(encode_request_frame(req))
            finally:
                pool.release(conn, reuse=True)
            resp = decode_response(raw)
            assert resp.is_ok, resp.error
            return codec.deserialize(resp.body, ProbeOut)
        finally:
            pool.close()

    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            out = await client.send(
                ReplicatedReporter, "p1", RBump(amount=1), returns=ProbeOut
            )
            primary_addr = out.address
            held, _ = await cluster.placement.standbys(ObjectId(RTNAME, "p1"))
            assert held and primary_addr not in held
            standby = next(
                s for s in cluster.servers if s.local_address == held[0]
            )
            key = (RTNAME, "p1")
            assert standby.replication_manager.replica_entry(key) is not None
            # Age the replica past the staleness bound: the readonly read
            # now PROXIES to the primary. The forward must carry tenant and
            # a strictly smaller remaining budget (the standby burned some).
            meta = standby.replication_manager._replica_meta[key]
            meta.recv_mono -= 60.0
            out = await raw_read(standby.local_address, 5000)
            assert out.address == primary_addr  # the proxy hop happened
            assert standby.read_scale_manager.stats.standby_forwards == 1
            assert out.tenant == "reader"
            assert 0 < out.budget_ms < 5000
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_replicated_registry,
            num_servers=3,
            server_kwargs={
                "replication_config": ReplicationConfig(
                    k=1, anti_entropy_interval=0.2, seat_ttl=0.2
                ),
                "read_scale_config": ReadScaleConfig(max_staleness_s=5.0),
                "qos_config": QosConfig(),
            },
        )
    )
