"""The ``watch`` operator CLI (ISSUE 11): trend table, --json, exit codes.

``_watch_rows`` is a pure function over DumpSeries snapshots — the table
the operator sees is asserted here on synthetic scrapes; the ``--demo``
one-shots run the CLI exactly as tier-1 CI does.
"""

import asyncio
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from rio_tpu.admin import SeriesSnapshot, _cli_main, _format_watch, _watch_rows
from rio_tpu.timeseries import SeriesSample


def _snap(address: str, per_sample: list[dict], **meta) -> SeriesSnapshot:
    rows = [
        SeriesSample(seq=i + 1, wall_ts=float(i), mono_ts=float(i),
                     node=address, gauges=g).to_row()
        for i, g in enumerate(per_sample)
    ]
    return SeriesSnapshot(address=address, node_seq=len(rows), rows=rows,
                          meta=meta)


def test_watch_rows_trend_table_from_synthetic_scrape():
    snapshots = [
        _snap(
            "10.0.0.2:9001",
            [
                {"rio.load.req_rate": 100.0, "rio.load.inflight": 4.0,
                 "rio.load.sheds": 0.0, "rio.handler.Svc.Get.p99_ms": 2.0},
                {"rio.load.req_rate": 100.0, "rio.load.inflight": 4.0,
                 "rio.load.sheds": 0.0, "rio.handler.Svc.Get.p99_ms": 2.0},
                {"rio.load.req_rate": 100.0, "rio.load.inflight": 4.0,
                 "rio.load.sheds": 0.0, "rio.handler.Svc.Get.p99_ms": 2.0,
                 "rio.handler.Svc.Put.p99_ms": 9.0},  # worst handler wins
            ],
            solver_mode="sinkhorn+delta",
            alerts=["p99_rising:rio.handler.Svc.Put.p99_ms"],
        ),
        _snap(
            "10.0.0.1:9001",
            [
                {"rio.load.req_rate": 50.0, "rio.load.inflight": 1.0,
                 "rio.load.sheds": 0.0},
                {"rio.load.req_rate": 80.0, "rio.load.inflight": 1.0,
                 "rio.load.sheds": 3.0},
            ],
        ),
    ]
    rows = _watch_rows(snapshots)
    # Sorted by address, regardless of scrape order.
    assert [r["address"] for r in rows] == ["10.0.0.1:9001", "10.0.0.2:9001"]
    quiet, busy = rows
    assert busy["rate"] == 100.0 and busy["rate_trend"] == "→"
    assert busy["p99_ms"] == 9.0 and busy["p99_trend"] == "↑"
    assert busy["solver_mode"] == "sinkhorn+delta"
    assert busy["alerts"] == ["p99_rising:rio.handler.Svc.Put.p99_ms"]
    assert quiet["rate"] == 80.0 and quiet["rate_trend"] == "↑"
    assert quiet["sheds"] == 3.0 and quiet["sheds_trend"] == "↑"
    assert quiet["p99_ms"] == 0.0  # no handler gauges at all
    assert quiet["solver_mode"] == "-"
    # The rendered table carries every row and the alert label.
    table = _format_watch(rows)
    assert "10.0.0.1:9001" in table and "10.0.0.2:9001" in table
    assert "p99_rising:rio.handler.Svc.Put.p99_ms" in table
    assert "sinkhorn+delta" in table


def test_watch_rows_tolerate_empty_snapshot():
    rows = _watch_rows([SeriesSnapshot(address="n:1")])
    assert rows[0]["samples"] == 0
    assert rows[0]["rate"] == 0.0 and rows[0]["rate_trend"] == "→"
    _format_watch(rows)  # renders without raising


def test_watch_demo_once_prints_trend_table(capsys):
    assert asyncio.run(_cli_main(["watch", "--demo", "--once"])) == 0
    out = capsys.readouterr().out
    assert "node" in out and "p99_ms" in out and "alerts" in out
    # Two demo nodes, each with a live sample window.
    body = [l for l in out.splitlines() if l.startswith("127.0.0.1:")]
    assert len(body) == 2


def test_watch_demo_json_is_machine_readable(capsys):
    assert asyncio.run(_cli_main(["--demo", "watch", "--json"])) == 0
    rows = json.loads(capsys.readouterr().out)
    assert len(rows) == 2
    for row in rows:
        assert row["samples"] > 0
        assert {"address", "rate", "p99_ms", "inflight", "sheds",
                "solver_mode", "alerts"} <= set(row)


def test_unreachable_cluster_exits_1(capsys):
    assert asyncio.run(_cli_main(["--nodes", "127.0.0.1:1", "watch",
                                  "--once"])) == 1
    assert asyncio.run(_cli_main(["--nodes", "127.0.0.1:1", "tail"])) == 1


def test_explain_without_subject_exits_2(capsys):
    assert asyncio.run(_cli_main(["--nodes", "127.0.0.1:1", "explain"])) == 2
    assert "missing TYPE ID" in capsys.readouterr().out
