"""Managed-state lifecycle integration: automatic load at activation,
handler-driven saves, state surviving object migration.

Reference: ``rio-rs/tests/object_state.rs`` and ``tests/state.rs``.
"""

import asyncio

import pytest

from rio_tpu import AppData, Registry, ServiceObject, handler, message
from rio_tpu.state import LocalState, StateProvider, managed_state
from rio_tpu.state.sqlite import SqliteState

from .server_utils import Cluster, run_integration_test


@message
class Deposit:
    amount: int = 0


@message
class Balance:
    total: int = 0
    loads: int = 0


@message
class AccountState:
    total: int = 0


class Account(ServiceObject):
    state = managed_state(AccountState)

    def __init__(self):
        self.loads = 0

    async def after_load(self, ctx: AppData) -> None:
        self.loads += 1

    @handler
    async def deposit(self, msg: Deposit, ctx: AppData) -> Balance:
        self.state.total += msg.amount
        await self.save_state(ctx)  # manual, handler-driven save
        return Balance(total=self.state.total, loads=self.loads)


def build_registry() -> Registry:
    return Registry().add_type(Account)


def run_with_state(body, state: StateProvider, num_servers=2):
    async def wrapped(cluster: Cluster):
        for s in cluster.servers:
            s.app_data.set(state, as_type=StateProvider)
        await body(cluster)

    asyncio.run(
        run_integration_test(wrapped, registry_builder=build_registry, num_servers=num_servers)
    )


def test_state_persists_across_deallocation():
    state = LocalState()

    async def body(cluster: Cluster):
        client = cluster.client()
        out = await client.send(Account, "a1", Deposit(amount=10), returns=Balance)
        assert out == Balance(total=10, loads=1)
        out = await client.send(Account, "a1", Deposit(amount=5), returns=Balance)
        assert out == Balance(total=15, loads=1)  # same live instance

        # Force deallocation (admin path), then hit it again: state reloads.
        addr = await cluster.allocation_address("Account", "a1")
        server = next(s for s in cluster.servers if s.local_address == addr)
        await server.shutdown_object("Account", "a1")
        assert not await cluster.is_allocated("Account", "a1")

        out = await client.send(Account, "a1", Deposit(amount=1), returns=Balance)
        assert out.total == 16  # persisted 15 + 1
        assert out.loads == 1  # fresh instance, loaded once
        client.close()

    run_with_state(body, state)


def test_state_sqlite_provider(tmp_path):
    state = SqliteState(str(tmp_path / "state.db"))

    async def body(cluster: Cluster):
        await state.prepare()
        client = cluster.client()
        await client.send(Account, "a1", Deposit(amount=7), returns=Balance)
        addr = await cluster.allocation_address("Account", "a1")
        server = next(s for s in cluster.servers if s.local_address == addr)
        await server.shutdown_object("Account", "a1")
        out = await client.send(Account, "a1", Deposit(amount=3), returns=Balance)
        assert out.total == 10
        client.close()

    run_with_state(body, state)


def test_missing_provider_fails_activation():
    async def body(cluster: Cluster):
        # No StateProvider registered: activation must fail with ALLOCATE,
        # not leave a half-initialized object behind.
        client = cluster.client()
        from rio_tpu.errors import RetryExhausted
        from rio_tpu.utils import ExponentialBackoff

        client._backoff = ExponentialBackoff(initial=1e-4, cap=1e-3, max_retries=3)
        with pytest.raises(RetryExhausted) as ei:
            await client.send(Account, "a1", Deposit(amount=1), returns=Balance)
        assert "ALLOCATE" in str(ei.value.last)
        assert not await cluster.is_allocated("Account", "a1")
        client.close()

    asyncio.run(run_integration_test(body, registry_builder=build_registry, num_servers=1))
