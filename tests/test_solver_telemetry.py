"""Solver convergence telemetry (ISSUE 11 tentpole): every solve path
records how hard it worked — iterations, final residual, warm-start
ratio, compile-vs-execute split, chunk timings — on ``SolveStats``.

Acceptance: telemetry present for full AND ``+delta`` solves, including
the mesh-sharded path on the 8-virtual-device CPU mesh (conftest).
"""

import pytest

from rio_tpu import ObjectId
from rio_tpu.object_placement.jax_placement import JaxObjectPlacement


class _Member:
    def __init__(self, address: str, active: bool = True) -> None:
        self.address = address
        self.active = active


def _members(n, dead=()):
    return [_Member(f"10.8.0.{i}:5000", i not in dead) for i in range(n)]


async def _seeded(n_obj, n_nodes, **kw):
    p = JaxObjectPlacement(node_axis_size=n_nodes, **kw)
    p.sync_members(_members(n_nodes))
    await p.assign_batch([ObjectId("T", str(i)) for i in range(n_obj)])
    await p.rebalance(delta=False)
    return p


def _assert_converged(stats, *, residual=True):
    assert stats.solver_iters > 0
    if residual:
        assert stats.residual >= 0.0
    # The compile listener is jax-version dependent; when it IS available
    # both halves of the split are present (exec clamps at 0 — nested
    # compile durations can slightly exceed the timed solve region).
    if stats.compile_ms >= 0.0:
        assert stats.exec_ms >= 0.0
    else:
        assert stats.exec_ms == -1.0


@pytest.mark.parametrize("mode", ["sinkhorn", "scaling"])
async def test_full_solve_records_convergence(mode):
    p = await _seeded(256, 4, mode=mode, n_iters=12)
    stats = p.stats
    # Small populations collapse to the class-level solve; either way the
    # configured solver ran and reported its convergence.
    assert stats.mode in (mode, f"{mode}+collapsed")
    _assert_converged(stats)
    assert stats.solver_iters == 12
    # A converged fixed-point solve leaves a tiny column-marginal violation.
    assert stats.residual < 1e-2
    # Full solves don't warm-start: the field reads "cold/not applicable".
    assert stats.warm_ratio <= 0.0


async def test_delta_solve_records_warm_start_ratio():
    p = await _seeded(512, 8, mode="sinkhorn", n_iters=12)
    p.sync_members(_members(8, dead={0}))
    await p.rebalance()
    stats = p.stats
    assert stats.mode == "sinkhorn+delta"
    _assert_converged(stats)
    # The delta warm-starts from the committed plan's potentials: the seed
    # coverage is a real fraction, not the -1 "n/a" sentinel.
    assert 0.0 <= stats.warm_ratio <= 1.0


async def test_hierarchical_solve_records_coarse_plus_fine_iters():
    p = await _seeded(256, 4, mode="hierarchical", n_iters=8)
    stats = p.stats
    assert stats.mode == "hierarchical"
    # Two stacked solves (coarse groups, then fine within groups).
    assert stats.solver_iters == 16
    _assert_converged(stats, residual=False)


async def test_mesh_sharded_solve_records_convergence():
    """The acceptance path: a sharded solve over the 8-virtual-device CPU
    mesh still reports its convergence telemetry."""
    from rio_tpu.parallel import make_mesh

    mesh = make_mesh()
    p = JaxObjectPlacement(mode="sinkhorn", n_iters=10, mesh=mesh)
    members = [f"10.8.1.{i}:70" for i in range(6)]
    p.sync_members(members)
    await p.assign_batch([ObjectId("MeshT", str(i)) for i in range(700)])
    await p.rebalance()
    stats = p.stats
    assert stats.mode == "sinkhorn"
    _assert_converged(stats, residual=False)
    assert stats.solver_iters == 10


async def test_greedy_solve_reports_no_iterations():
    """Non-iterative modes must not fake convergence numbers."""
    p = await _seeded(128, 4, mode="greedy")
    assert p.stats.solver_iters == 0
    assert p.stats.residual == -1.0


async def test_history_gauges_carry_convergence_trend():
    p = await _seeded(512, 8, mode="sinkhorn", n_iters=12)
    p.sync_members(_members(8, dead={0}))
    await p.rebalance()
    g = p.stats.history_gauges()
    assert g["rio.placement_solve.history.residual_last"] >= 0.0
    assert (
        g["rio.placement_solve.history.residual_max"]
        >= g["rio.placement_solve.history.residual_last"]
    )
    if p.stats.compile_ms >= 0.0 or any(
        s.compile_ms >= 0.0 for s in p.stats.history
    ):
        assert g["rio.placement_solve.history.compile_ms_total"] >= 0.0
    assert g["rio.placement_solve.history.delta_fraction"] > 0.0


async def test_mesh_hierarchical_second_solve_warm_starts():
    """ISSUE 18 satellite: the mesh branch used to drop ``coarse_g_init``
    on the floor AND never return the potentials, so mesh solves could
    never warm-start. Now the seed threads in and the pmean'd replicated
    potentials persist: a second full solve on an UNCHANGED cluster
    reports a positive warm ratio."""
    from rio_tpu.parallel import make_mesh

    p = JaxObjectPlacement(mode="hierarchical", n_iters=8, mesh=make_mesh())
    p.sync_members(_members(12))
    await p.assign_batch([ObjectId("WarmT", str(i)) for i in range(3000)])
    await p.rebalance(delta=False)
    first = p.stats
    assert first.mode.startswith("hierarchical")
    assert first.warm_ratio <= 0.0  # nothing to seed from yet
    await p.rebalance(delta=False)
    second = p.stats
    assert second.mode.startswith("hierarchical")
    assert second.warm_ratio > 0.0, second
    _assert_converged(second, residual=False)


async def test_mesh_chunked_composed_solve_records_chunk_telemetry(monkeypatch):
    """The composed mesh x chunk dispatch stamps its shape onto SolveStats:
    ``+mesh_chunk`` mode suffix, chunk count, device count, and per-chunk
    wall timings (first chunk carries the compile)."""
    from rio_tpu.object_placement import jax_placement as jp
    from rio_tpu.parallel import make_mesh

    monkeypatch.setattr(jp, "_HIER_CHUNK_ROWS", 64)
    p = JaxObjectPlacement(mode="hierarchical", n_iters=8, mesh=make_mesh())
    p.sync_members(_members(12))
    await p.assign_batch([ObjectId("ChunkT", str(i)) for i in range(3000)])
    await p.rebalance(delta=False)
    stats = p.stats
    assert stats.mode == "hierarchical+mesh_chunk"
    assert stats.chunks > 1
    assert stats.devices == 8
    assert len(stats.chunk_ms) == stats.chunks
    assert all(ms > 0.0 for ms in stats.chunk_ms)
    # Compile-vs-exec split: the first chunk pays the one-time compile.
    assert stats.chunk_ms[0] >= max(stats.chunk_ms[1:])
    g = stats.history_gauges()
    assert g["rio.placement_solve.history.chunks_last"] == float(stats.chunks)
    assert g["rio.placement_solve.history.chunks_max"] >= float(stats.chunks)
    assert g["rio.placement_solve.history.devices_last"] == 8.0
    assert (
        g["rio.placement_solve.history.first_chunk_ms_last"]
        == stats.chunk_ms[0]
    )
    assert (
        g["rio.placement_solve.history.first_chunk_ms_max"]
        >= stats.chunk_ms[0]
    )


async def test_mesh_chunk_gauges_export_through_fake_otel(monkeypatch):
    """The new telemetry flows to the exporter with zero otel changes:
    ``stats_gauges`` flattens the ``devices`` scalar automatically and the
    history summary carries the chunk fields."""
    from . import fake_otel
    from rio_tpu.object_placement import jax_placement as jp
    from rio_tpu.parallel import make_mesh

    monkeypatch.setattr(jp, "_HIER_CHUNK_ROWS", 64)
    p = JaxObjectPlacement(mode="hierarchical", n_iters=8, mesh=make_mesh())
    p.sync_members(_members(12))
    await p.assign_batch([ObjectId("OtelT", str(i)) for i in range(3000)])
    await p.rebalance(delta=False)

    handle = fake_otel.install()
    try:
        from rio_tpu.otel import otlp_metrics_exporter, stats_gauges

        def snapshot():
            return {
                **stats_gauges(placement_solve=p.stats),
                **p.stats.history_gauges(),
            }

        provider = otlp_metrics_exporter(snapshot, interval=9999.0)
        exporter = handle.metric_exporters[-1]
        provider.force_flush()
        exported = exporter.exported[-1]
        assert exported["rio.placement_solve.devices"] == 8.0
        assert exported["rio.placement_solve.chunks"] > 1.0
        assert exported["rio.placement_solve.history.chunks_last"] > 1.0
        assert exported["rio.placement_solve.history.devices_last"] == 8.0
        assert exported["rio.placement_solve.history.first_chunk_ms_last"] > 0.0
    finally:
        fake_otel.uninstall(handle)
