"""Live-migration handoff protocol: single-activation fencing, state
transfer, rebalancer actuation, and failure-path recovery.

The e2e test is the acceptance bar for the subsystem: a placement-daemon
rebalance moves seated stateful objects between live nodes under concurrent
client traffic with zero lost updates and zero double-activations, and
reminder-shard seat rows flow through the same ``apply_moves`` path.
"""

import asyncio
import random

import pytest

from rio_tpu import (
    AdminCommand,
    AppData,
    LocalObjectPlacement,
    LocalStorage,
    Registry,
    ServiceObject,
    handler,
    message,
    type_name,
)
from rio_tpu.commands import ServerInfo
from rio_tpu.errors import ObjectNotFound
from rio_tpu.migration import (
    CONTROL_TYPE,
    INBOX_TYPE,
    InstallState,
    MigrationAck,
    MigrationConfig,
    MigrationManager,
    MigrationStats,
)
from rio_tpu.object_placement.jax_placement import JaxObjectPlacement
from rio_tpu.placement_daemon import PlacementDaemonConfig
from rio_tpu.protocol import ResponseError
from rio_tpu.registry import ObjectId
from rio_tpu.reminders.daemon import SHARD_TYPE
from rio_tpu.state import LocalState, StateProvider, managed_state

from .server_utils import (
    Cluster,
    run_integration_test,
    wait_for_active_members,
)

# Module-level activation guards, reset by each test that uses them.
ACTIVATIONS: dict[str, int] = {}  # id -> lifetime LOAD count
ACTIVE: dict[str, str] = {}  # id -> address currently holding a live instance
DOUBLE: list[str] = []  # ids that activated while already active somewhere


def _reset_guards() -> None:
    ACTIVATIONS.clear()
    ACTIVE.clear()
    DOUBLE.clear()


@message
class Add:
    amount: int = 0


@message
class Get:
    pass


@message
class Totals:
    total: int = 0
    hot: int = 0
    address: str = ""


@message
class CounterState:
    total: int = 0


class Counter(ServiceObject):
    """Stateful actor with both managed and volatile migratable state.

    ``hot`` mirrors ``state.total`` but lives only in memory: after any
    number of coordinated handoffs the two must still be equal — a fresh
    (non-migrated) activation would reset ``hot`` to 0 and expose a lost
    volatile snapshot.
    """

    state = managed_state(CounterState)

    def __init__(self):
        self.hot = 0

    def __migrate_state__(self):
        return {"hot": self.hot}

    def __restore_state__(self, value):
        self.hot = int(value["hot"])

    async def after_load(self, ctx: AppData) -> None:
        ACTIVATIONS[self.id] = ACTIVATIONS.get(self.id, 0) + 1
        addr = ctx.get(ServerInfo).address
        if self.id in ACTIVE:
            DOUBLE.append(self.id)
        ACTIVE[self.id] = addr

    async def before_shutdown(self, ctx: AppData) -> None:
        ACTIVE.pop(self.id, None)

    @handler
    async def add(self, msg: Add, ctx: AppData) -> Totals:
        self.state.total += msg.amount
        self.hot += msg.amount
        await self.save_state(ctx)
        return Totals(
            total=self.state.total, hot=self.hot, address=ctx.get(ServerInfo).address
        )

    @handler
    async def get(self, msg: Get, ctx: AppData) -> Totals:
        return Totals(
            total=self.state.total, hot=self.hot, address=ctx.get(ServerInfo).address
        )


def build_registry() -> Registry:
    return Registry().add_type(Counter)


# ---------------------------------------------------------------------------
# Admin-command handoff: managed + volatile state survive, stats move
# ---------------------------------------------------------------------------


def test_admin_migrate_moves_state_and_volatile():
    _reset_guards()
    state = LocalState()

    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            out = await client.send(Counter, "c1", Add(amount=7), returns=Totals)
            source_addr = out.address
            out = await client.send(Counter, "c1", Add(amount=3), returns=Totals)
            assert (out.total, out.hot) == (10, 10)

            source = next(
                s for s in cluster.servers if s.local_address == source_addr
            )
            target = next(
                s for s in cluster.servers if s.local_address != source_addr
            )
            source.admin_sender().send(
                AdminCommand.migrate("Counter", "c1", target.local_address)
            )
            deadline = asyncio.get_event_loop().time() + 10.0
            while asyncio.get_event_loop().time() < deadline:
                if source.migration_manager.stats.completed:
                    break
                await asyncio.sleep(0.02)
            assert source.migration_manager.stats.completed == 1
            assert source.migration_manager.stats.started == 1
            assert source.migration_manager.stats.aborted == 0
            assert source.migration_manager.stats.state_bytes > 0
            assert target.migration_manager.stats.installs == 1

            # Directory flipped; source no longer holds the instance.
            assert (
                await cluster.allocation_address("Counter", "c1")
                == target.local_address
            )
            assert not source.registry.has("Counter", "c1")

            # The next request activates on the target with BOTH kinds of
            # state intact — managed via the backend, volatile via the
            # inline transfer.
            out = await client.send(Counter, "c1", Add(amount=1), returns=Totals)
            assert out.address == target.local_address
            assert (out.total, out.hot) == (11, 11)
            assert ACTIVATIONS["c1"] == 2
            assert DOUBLE == []
        finally:
            client.close()

    async def wrapped(cluster: Cluster):
        for s in cluster.servers:
            s.app_data.set(state, as_type=StateProvider)
        await body(cluster)

    asyncio.run(
        run_integration_test(wrapped, registry_builder=build_registry, num_servers=2)
    )


# ---------------------------------------------------------------------------
# E2E acceptance: daemon rebalance = live handoffs under concurrent traffic
# ---------------------------------------------------------------------------


def test_rebalance_actuates_live_handoffs_under_traffic():
    """Boot a third node into a loaded 2-node cluster: the placement daemon
    re-solves on the liveness change and every solver move runs as a
    coordinated handoff between LIVE nodes, while clients keep writing.
    Zero lost updates, zero double-activations, volatile state rides along,
    and a reminder-shard seat row flips through the same move path."""
    _reset_guards()
    state = LocalState()
    placement = JaxObjectPlacement(mode="greedy", move_cost=0.5)
    daemon_cfg = PlacementDaemonConfig(
        poll_interval=0.1, debounce=0.05, min_rebalance_interval=0.1
    )
    n_objects = 12
    keys = [f"c{i}" for i in range(n_objects)]

    async def body(cluster: Cluster):
        client = cluster.client()
        third = None
        third_task = None
        acked = {k: 0 for k in keys}
        failures: list[str] = []
        stop_traffic = asyncio.Event()

        async def traffic():
            while not stop_traffic.is_set():
                k = random.choice(keys)
                for attempt in range(3):
                    try:
                        await client.send(Counter, k, Add(amount=1), returns=Totals)
                        acked[k] += 1
                        break
                    except Exception:
                        if attempt == 2:
                            failures.append(k)
                        await asyncio.sleep(0.05)
                await asyncio.sleep(0.005)

        try:
            for k in keys:
                await client.send(Counter, k, Add(amount=1), returns=Totals)
                acked[k] += 1
            # Seed a reminder-shard seat row beside the object population.
            from rio_tpu.object_placement import ObjectPlacementItem

            shard_oid = ObjectId(SHARD_TYPE, "3")
            await placement.update(
                ObjectPlacementItem(shard_oid, cluster.addresses[0])
            )

            traffic_task = asyncio.create_task(traffic())
            await asyncio.sleep(0.3)

            # Boot the third node mid-traffic: its registration is the
            # liveness change that arms every daemon.
            from rio_tpu import Server
            from rio_tpu.cluster.membership_protocol import LocalClusterProvider

            third = Server(
                address="127.0.0.1:0",
                registry=build_registry(),
                cluster_provider=LocalClusterProvider(cluster.members),
                object_placement_provider=placement,
                app_data=AppData().set(state, as_type=StateProvider),
                placement_daemon=True,
                placement_daemon_config=daemon_cfg,
            )
            await third.prepare()
            await third.bind()
            third_task = asyncio.create_task(third.run())
            await wait_for_active_members(cluster.members, 3)

            managers = [s.migration_manager for s in cluster.servers] + [
                third.migration_manager
            ]
            deadline = asyncio.get_event_loop().time() + 20.0
            while asyncio.get_event_loop().time() < deadline:
                if sum(m.stats.completed for m in managers) > 0:
                    break
                await asyncio.sleep(0.05)
            assert sum(m.stats.completed for m in managers) > 0, (
                "no coordinated handoff ran after the liveness change"
            )
            await asyncio.sleep(0.5)  # let a little post-move traffic land

            stop_traffic.set()
            await traffic_task
            assert not failures, f"writes failed outright: {failures}"

            # Zero lost updates + volatile state followed every move.
            all_addrs = set(cluster.addresses) | {third.local_address}
            for k in keys:
                out = await client.send(Counter, k, Get(), returns=Totals)
                assert out.total == acked[k], (
                    f"{k}: {acked[k]} acked writes but total={out.total}"
                )
                assert out.hot == out.total, (
                    f"{k}: volatile state lost in handoff "
                    f"(hot={out.hot}, total={out.total})"
                )
                assert out.address in all_addrs
            assert DOUBLE == [], f"double activations: {DOUBLE}"

            # Reminder-shard rows ride the same apply_moves path: ask a
            # coordinator to move the seeded seat row; with no live
            # activation to hand off it must flip the row directly.
            mover = cluster.servers[0].migration_manager
            src = await placement.lookup(shard_oid)
            dst = next(a for a in sorted(all_addrs) if a != src)
            moved = await mover.apply_moves([(f"{SHARD_TYPE}.3", src, dst)])
            assert moved == 1
            assert await placement.lookup(shard_oid) == dst
        finally:
            stop_traffic.set()
            client.close()
            if third_task is not None:
                third_task.cancel()
                await asyncio.gather(third_task, return_exceptions=True)

    async def wrapped(cluster: Cluster):
        for s in cluster.servers:
            s.app_data.set(state, as_type=StateProvider)
        await body(cluster)

    asyncio.run(
        run_integration_test(
            wrapped,
            registry_builder=build_registry,
            num_servers=2,
            placement=placement,
            timeout=60.0,
            server_kwargs={
                "placement_daemon": True,
                "placement_daemon_config": daemon_cfg,
            },
        )
    )


# ---------------------------------------------------------------------------
# Batched bursts + target-initiated prefetch: a grouped drain moves many
# keys through few RPCs and skips the in-window transfer on unchanged state
# ---------------------------------------------------------------------------


def test_batched_drain_prefetch_hits_skip_pinned_transfer():
    """Drain every key off one node through apply_moves: the plan is grouped
    into MigrateBatch bursts (chunked at batch_size), the target prefetches
    each volatile snapshot before the pin, and — with no traffic mutating
    state between prefetch and pin — every handoff is a prefetch HIT: zero
    pin-time installs, volatile state still intact on the target."""
    _reset_guards()
    state = LocalState()
    n_objects = 12
    keys = [f"b{i}" for i in range(n_objects)]

    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            owners: dict[str, list[str]] = {s.local_address: [] for s in cluster.servers}
            for k in keys:
                out = await client.send(Counter, k, Add(amount=3), returns=Totals)
                owners[out.address].append(k)
            source_addr = max(owners, key=lambda a: len(owners[a]))
            drained = owners[source_addr]
            source = next(s for s in cluster.servers if s.local_address == source_addr)
            target = next(s for s in cluster.servers if s.local_address != source_addr)

            moves = [
                (f"Counter.{k}", source_addr, target.local_address) for k in drained
            ]
            moved = await source.migration_manager.apply_moves(moves)
            assert moved == len(drained)

            sstats = source.migration_manager.stats
            # Grouping: one (source, target) pair chunked at batch_size=4.
            expect_bursts = -(-len(drained) // 4)  # ceil
            assert sstats.batches == expect_bursts, sstats
            assert sstats.batch_keys == len(drained)
            # Prefetch served every snapshot pre-pin, and nothing changed
            # state in between, so every handoff skipped the in-window
            # transfer: no pin-time install reached the target's inbox.
            assert sstats.prefetch_served == len(drained)
            assert sstats.prefetch_hits == len(drained)
            assert sstats.prefetch_misses == 0
            assert target.migration_manager.stats.installs == 0
            assert sstats.state_bytes > 0  # the prefetch moved real bytes
            # Pinned-window accounting covers every handoff.
            assert sstats.pinned_windows == len(drained)
            assert sstats.pinned_ms_total > 0.0
            assert (
                sstats.pinned_le_1ms
                + sstats.pinned_le_10ms
                + sstats.pinned_le_100ms
                + sstats.pinned_gt_100ms
                == len(drained)
            )
            assert not source.migration_manager._pinned

            # Every drained key serves from the target with BOTH kinds of
            # state intact — volatile arrived via the prefetch stash alone.
            for k in drained:
                out = await client.send(Counter, k, Get(), returns=Totals)
                assert out.address == target.local_address
                assert (out.total, out.hot) == (3, 3), (k, out)
            assert DOUBLE == []
        finally:
            client.close()

    async def wrapped(cluster: Cluster):
        for s in cluster.servers:
            s.app_data.set(state, as_type=StateProvider)
        await body(cluster)

    asyncio.run(
        run_integration_test(
            wrapped,
            registry_builder=build_registry,
            num_servers=2,
            server_kwargs={"migration_config": MigrationConfig(batch_size=4)},
        )
    )


# ---------------------------------------------------------------------------
# Chaos: source dies mid-migration → exactly-once reactivation from
# last persisted state
# ---------------------------------------------------------------------------


def test_source_death_mid_migration_reactivates_once_from_persisted_state():
    _reset_guards()
    state = LocalState()

    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            out = await client.send(Counter, "c1", Add(amount=5), returns=Totals)
            source = next(
                s for s in cluster.servers if s.local_address == out.address
            )
            survivor = next(
                s for s in cluster.servers if s.local_address != out.address
            )

            # The transfer RPC dies mid-handoff (network partition between
            # deactivate and install): the migration must abort with the
            # managed snapshot already persisted and the directory untouched.
            async def failing_install(target, oid, payload):
                raise OSError("network partition mid-transfer")

            source.migration_manager._install_on = failing_install
            ok = await source.migration_manager.migrate_out(
                ObjectId("Counter", "c1"), survivor.local_address
            )
            assert ok is False
            assert source.migration_manager.stats.aborted == 1
            assert not source.registry.has("Counter", "c1")
            assert (
                await cluster.allocation_address("Counter", "c1")
                == source.local_address
            )

            # Now the wounded source dies outright.
            source.admin_sender().send(AdminCommand.server_exit())
            deadline = asyncio.get_event_loop().time() + 10.0
            while asyncio.get_event_loop().time() < deadline:
                if not await cluster.members.is_active(source.local_address):
                    break
                await asyncio.sleep(0.02)

            # First read re-seats on the survivor and reloads the LAST
            # PERSISTED state — exactly one reactivation, nothing doubled.
            out = await client.send(Counter, "c1", Get(), returns=Totals)
            assert out.address == survivor.local_address
            assert out.total == 5  # the pre-abort snapshot survived
            assert out.hot == 0  # volatile is gone by design: never installed
            assert ACTIVATIONS["c1"] == 2  # initial + exactly one recovery
        finally:
            client.close()

    async def wrapped(cluster: Cluster):
        for s in cluster.servers:
            s.app_data.set(state, as_type=StateProvider)
        await body(cluster)

    asyncio.run(
        run_integration_test(wrapped, registry_builder=build_registry, num_servers=2)
    )


# ---------------------------------------------------------------------------
# Chaos: source fails partway through a BATCH → the completed prefix keeps
# its flips + fences, the rest degrades to lazy re-seat, nothing stays pinned
# ---------------------------------------------------------------------------


def test_source_failure_mid_batch_leaves_no_stranded_pins():
    """A burst loses its transfer path after the first key (partition /
    source dying): the already-flipped key serves from the target behind its
    fence, every other key aborts per-key WITHOUT stranding a pin or
    touching the directory, and when the source then dies outright the
    leftover keys re-seat exactly once from persisted state."""
    _reset_guards()
    state = LocalState()
    n_objects = 6
    keys = [f"x{i}" for i in range(n_objects)]

    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            owners: dict[str, list[str]] = {s.local_address: [] for s in cluster.servers}
            for k in keys:
                out = await client.send(Counter, k, Add(amount=2), returns=Totals)
                owners[out.address].append(k)
            source_addr = max(owners, key=lambda a: len(owners[a]))
            batch = owners[source_addr]
            assert len(batch) >= 2, owners  # need a prefix AND a remainder
            source = next(s for s in cluster.servers if s.local_address == source_addr)
            survivor = next(
                s for s in cluster.servers if s.local_address != source_addr
            )

            # The transfer path dies after one install (prefetch is off, so
            # every handoff must cross it; handoff_concurrency=1 makes the
            # failure point deterministic).
            real_install = source.migration_manager._install_on
            calls = {"n": 0}

            async def dying_install(target, oid, payload):
                calls["n"] += 1
                if calls["n"] > 1:
                    raise OSError("source lost its network mid-batch")
                await real_install(target, oid, payload)

            source.migration_manager._install_on = dying_install

            # The SURVIVOR coordinates: the burst travels as one
            # MigrateBatch RPC to the source's control actor.
            moves = [
                (f"Counter.{k}", source_addr, survivor.local_address) for k in batch
            ]
            moved = await survivor.migration_manager.apply_moves(moves)
            assert moved == 1  # the pre-failure prefix
            sstats = source.migration_manager.stats
            assert sstats.completed == 1
            assert sstats.aborted == len(batch) - 1
            # The safety core: nothing is left pinned, and only the
            # completed key's row flipped.
            assert not source.migration_manager._pinned
            flipped = [
                k
                for k in batch
                if await cluster.allocation_address("Counter", k)
                == survivor.local_address
            ]
            assert len(flipped) == 1
            # Its fence holds: the source refuses with a redirect rather
            # than re-activating (the epoch fence survives the failed tail).
            assert ("Counter", flipped[0]) in source.migration_manager._fenced
            out = await client.send(Counter, flipped[0], Get(), returns=Totals)
            assert out.address == survivor.local_address
            assert (out.total, out.hot) == (2, 2)

            # Failed keys re-activate on the (still live) source from
            # persisted state — volatile lost by design, nothing doubled.
            for k in batch:
                if k == flipped[0]:
                    continue
                out = await client.send(Counter, k, Get(), returns=Totals)
                assert out.address == source_addr
                assert out.total == 2
            assert DOUBLE == []

            # Now the wounded source dies outright: the leftover keys
            # re-seat on the survivor exactly once, from persisted state.
            source.admin_sender().send(AdminCommand.server_exit())
            deadline = asyncio.get_event_loop().time() + 10.0
            while asyncio.get_event_loop().time() < deadline:
                if not await cluster.members.is_active(source_addr):
                    break
                await asyncio.sleep(0.02)
            # server_exit is a HARD exit (no shutdown lifecycle): a real
            # process death takes its activations with it, but the
            # in-process guard can't see that — retire them by hand so
            # the survivor's re-seats aren't misread as doubles.
            for k, addr in list(ACTIVE.items()):
                if addr == source_addr:
                    ACTIVE.pop(k)
            for k in batch:
                out = await client.send(Counter, k, Get(), returns=Totals)
                assert out.address == survivor.local_address
                assert out.total == 2
            assert DOUBLE == []
        finally:
            client.close()

    async def wrapped(cluster: Cluster):
        for s in cluster.servers:
            s.app_data.set(state, as_type=StateProvider)
        await body(cluster)

    asyncio.run(
        run_integration_test(
            wrapped,
            registry_builder=build_registry,
            num_servers=2,
            server_kwargs={
                "migration_config": MigrationConfig(
                    prefetch=False, handoff_concurrency=1
                )
            },
        )
    )


def test_apply_moves_whole_burst_failure_degrades_safely():
    """The source is gone before the batch RPC even lands (claimed active by
    a stale membership view): the burst fails as a unit, apply_moves counts
    one abort and returns without raising — rows stand for the lazy path."""

    async def run():
        from rio_tpu.cluster.storage import Member

        members = LocalStorage()
        # Stale view: claimed active but nothing listens there.
        await members.push(Member(ip="1.1.1.1", port=1, active=True))
        mgr = MigrationManager(
            address="9.9.9.9:9",
            registry=Registry().add_type(Counter),
            placement=LocalObjectPlacement(),
            members_storage=members,
            app_data=AppData(),
            config=MigrationConfig(prefetch=False),
        )

        class _DeadClient:
            def send(self, *a, **kw):
                raise OSError("connection refused")

            def close(self):
                pass

        mgr._client = _DeadClient()
        moved = await mgr.apply_moves(
            [("Counter.a", "1.1.1.1:1", "2.2.2.2:2"),
             ("Counter.b", "1.1.1.1:1", "2.2.2.2:2")]
        )
        assert moved == 0
        assert mgr.stats.aborted == 1  # one burst, one abort
        assert not mgr._pinned

    asyncio.run(run())


# ---------------------------------------------------------------------------
# Node-scoped control plane routing
# ---------------------------------------------------------------------------


def test_node_scoped_inbox_routes_by_address():
    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            target = cluster.servers[1]
            # Sent blind through the cluster client: whichever node takes
            # the request must redirect to the id-named node, which serves.
            ack = await client.send(
                INBOX_TYPE,
                target.local_address,
                InstallState(type_name="Counter", object_id="x", payload=b"\x01"),
                returns=MigrationAck,
            )
            assert ack.ok
            assert ("Counter", "x") in target.migration_manager._stash
            # No directory row was written for the control actor.
            assert (
                await cluster.placement.lookup(
                    ObjectId(INBOX_TYPE, target.local_address)
                )
                is None
            )
        finally:
            client.close()

    asyncio.run(
        run_integration_test(body, registry_builder=build_registry, num_servers=2)
    )


# ---------------------------------------------------------------------------
# Unit: refusal/fence state machine
# ---------------------------------------------------------------------------


def _bare_manager(address="1.1.1.1:1", registry=None) -> MigrationManager:
    return MigrationManager(
        address=address,
        registry=registry or Registry(),
        placement=LocalObjectPlacement(),
        members_storage=LocalStorage(),
        app_data=AppData(),
    )


def test_pin_and_fence_refusals():
    async def run():
        mgr = _bare_manager()
        oid = ObjectId("Counter", "c1")

        assert await mgr.refusal_for(oid) is None
        assert mgr.activation_refusal(oid) is None

        mgr._pinned[("Counter", "c1")] = "2.2.2.2:2"
        err = await mgr.refusal_for(oid)
        assert err is not None and err.kind == ResponseError.deallocate().kind
        err = mgr.activation_refusal(oid)
        assert err is not None and err.kind == ResponseError.deallocate().kind
        mgr._pinned.clear()

        import time

        mgr._fenced[("Counter", "c1")] = ("2.2.2.2:2", time.monotonic())
        err = await mgr.refusal_for(oid)
        assert err is not None and err.kind == ResponseError.redirect("x").kind
        assert err.detail == "2.2.2.2:2"  # directory empty → remembered target
        err = mgr.activation_refusal(oid)
        assert err is not None and err.detail == "2.2.2.2:2"

        # The fence clears itself when the directory seats the object
        # back on this node (a later solve moved it home).
        from rio_tpu.object_placement import ObjectPlacementItem

        await mgr.placement.update(ObjectPlacementItem(oid, mgr.address))
        assert await mgr.refusal_for(oid) is None
        assert ("Counter", "c1") not in mgr._fenced
        assert mgr.stats.refusals == 4

    asyncio.run(run())


def test_split_key_prefers_longest_registered_type():
    @type_name("acme.Counter.v2")
    class Dotted(ServiceObject):
        pass

    mgr = _bare_manager(registry=Registry().add_type(Dotted))
    assert mgr._split_key("acme.Counter.v2.user.42") == ObjectId(
        "acme.Counter.v2", "user.42"
    )
    # Framework shard rows parse without being registry types.
    assert mgr._split_key(f"{SHARD_TYPE}.7") == ObjectId(SHARD_TYPE, "7")
    # Foreign rows degrade to a first-dot split; the dotless are unroutable.
    assert mgr._split_key("Other.x") == ObjectId("Other", "x")
    assert mgr._split_key("nodots") is None


def test_apply_moves_flips_dead_source_and_shard_rows():
    async def run():
        members = LocalStorage()
        await members.set_active("9.9.9.9", 9)
        placement = LocalObjectPlacement()
        mgr = MigrationManager(
            address="9.9.9.9:9",
            registry=Registry(),
            placement=placement,
            members_storage=members,
            app_data=AppData(),
        )
        from rio_tpu.object_placement import ObjectPlacementItem

        shard = ObjectId(SHARD_TYPE, "3")
        dead_obj = ObjectId("Ghost", "g1")
        await placement.update(ObjectPlacementItem(shard, "1.1.1.1:1"))
        await placement.update(ObjectPlacementItem(dead_obj, "1.1.1.1:1"))

        moved = await mgr.apply_moves(
            [
                (f"{SHARD_TYPE}.3", "1.1.1.1:1", "2.2.2.2:2"),
                ("Ghost.g1", "1.1.1.1:1", "2.2.2.2:2"),  # dead src, foreign type
                ("Ghost.g1", "3.3.3.3:3", "3.3.3.3:3"),  # src==dst: skipped
                ("nodots", "1.1.1.1:1", "2.2.2.2:2"),  # unroutable: skipped
            ]
        )
        assert moved == 2
        assert await placement.lookup(shard) == "2.2.2.2:2"
        assert await placement.lookup(dead_obj) == "2.2.2.2:2"
        assert mgr.stats.seat_flips == 2

        # A row someone already re-seated must NOT be flipped again.
        await placement.update(ObjectPlacementItem(shard, "5.5.5.5:5"))
        moved = await mgr.apply_moves([(f"{SHARD_TYPE}.3", "1.1.1.1:1", "2.2.2.2:2")])
        assert moved == 0
        assert await placement.lookup(shard) == "5.5.5.5:5"

    asyncio.run(run())


def test_migrate_out_refuses_bad_targets():
    async def run():
        mgr = _bare_manager()
        oid = ObjectId("Counter", "c1")
        assert not await mgr.migrate_out(oid, "")  # no target
        assert not await mgr.migrate_out(oid, mgr.address)  # self-move
        assert not await mgr.migrate_out(oid, "2.2.2.2:2")  # target not active
        assert mgr.stats.started == 0

        mgr._pinned[("Counter", "c1")] = "3.3.3.3:3"
        members = mgr.members_storage
        await members.set_active("2.2.2.2", 2)
        assert not await mgr.migrate_out(oid, "2.2.2.2:2")  # already pinned

    asyncio.run(run())


# ---------------------------------------------------------------------------
# Unit: registry deactivation under the dispatch lock
# ---------------------------------------------------------------------------


def test_registry_deactivate_fences_queued_dispatch():
    """A request already queued on the object lock when deactivation wins
    must surface ObjectNotFound, not run against the removed instance."""

    @message
    class Slow:
        pass

    release = asyncio.Event()
    runs: list[str] = []

    class Sleepy(ServiceObject):
        @handler
        async def slow(self, msg: Slow, ctx: AppData) -> int:
            runs.append(self.id)
            await release.wait()
            return 1

    async def run():
        reg = Registry().add_type(Sleepy)
        app = AppData()
        reg.insert("Sleepy", "s1", reg.new_from_type("Sleepy", "s1"))

        from rio_tpu import codec

        first = asyncio.create_task(
            reg.send_raw("Sleepy", "s1", "Slow", codec.serialize(Slow()), app)
        )
        await asyncio.sleep(0.01)  # first holds the lock
        # Lock waiters wake FIFO: deactivation queues ahead of the request.
        deact = asyncio.create_task(reg.deactivate("Sleepy", "s1", app))
        await asyncio.sleep(0.01)
        queued = asyncio.create_task(
            reg.send_raw("Sleepy", "s1", "Slow", codec.serialize(Slow()), app)
        )
        await asyncio.sleep(0.01)
        release.set()

        await first  # completes normally
        assert await deact is True
        with pytest.raises(ObjectNotFound):
            await queued
        assert runs == ["s1"]  # the queued request never ran a handler
        assert not reg.has("Sleepy", "s1")

        # Deactivating a non-live object reports False.
        assert await reg.deactivate("Sleepy", "s1", app) is False

    asyncio.run(run())


def test_registry_deactivate_runs_snapshot_under_lock():
    async def run():
        reg = Registry().add_type(Counter)
        app = AppData()
        obj = reg.new_from_type("Counter", "c9")
        obj.hot = 42
        reg.insert("Counter", "c9", obj)

        seen: list[int] = []

        async def snap(o):
            seen.append(o.hot)

        assert await reg.deactivate("Counter", "c9", app, before_remove=snap)
        assert seen == [42]
        assert not reg.has("Counter", "c9")

    asyncio.run(run())


# ---------------------------------------------------------------------------
# Satellite: otel gauges
# ---------------------------------------------------------------------------


def test_stats_gauges_flatten_and_exporter_gates():
    from rio_tpu.otel import otlp_metrics_exporter, stats_gauges
    from rio_tpu.placement_daemon import PlacementDaemonStats

    gauges = stats_gauges(
        placement_daemon=PlacementDaemonStats(polls=4, moves=2, bursts=1),
        migration=MigrationStats(started=3, state_bytes=128, prefetch_hits=2),
        absent=None,
    )
    assert gauges["rio.placement_daemon.polls"] == 4.0
    assert gauges["rio.placement_daemon.moves"] == 2.0
    assert gauges["rio.placement_daemon.bursts"] == 1.0
    assert gauges["rio.migration.started"] == 3.0
    assert gauges["rio.migration.state_bytes"] == 128.0
    # The batched-engine counters export like every other stats field.
    assert gauges["rio.migration.prefetch_hits"] == 2.0
    for key in ("batches", "batch_keys", "prefetch_served", "prefetch_misses",
                "pinned_windows", "pinned_ms_total", "pinned_ms_max",
                "pinned_le_1ms", "pinned_le_10ms", "pinned_le_100ms",
                "pinned_gt_100ms"):
        assert f"rio.migration.{key}" in gauges, key
    assert not any(k.startswith("rio.absent") for k in gauges)

    # The SDK-backed exporter is optional and must gate loudly without it.
    with pytest.raises(ImportError, match="opentelemetry"):
        otlp_metrics_exporter(lambda: gauges)


def test_server_gauges_cover_wired_subsystems():
    from rio_tpu.otel import server_gauges

    async def body(cluster: Cluster):
        gauges = server_gauges(cluster.servers[0])
        assert "rio.migration.started" in gauges
        assert "rio.registry.objects" in gauges

    asyncio.run(
        run_integration_test(body, registry_builder=build_registry, num_servers=1)
    )
