"""Driver-contract checks: entry() compiles; dryrun_multichip runs on 8 CPUs."""

import pathlib
import sys

import jax
import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import __graft_entry__ as graft


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    out = np.asarray(jax.block_until_ready(out))
    assert out.shape == (args[0].shape[0],)
    assert out.min() >= 0 and out.max() < args[0].shape[1]


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)
