"""Parity tests: fused Pallas Sinkhorn vs the reference XLA implementation.

Run in interpreter mode on the CPU test mesh (conftest pins
JAX_PLATFORMS=cpu); on real TPU hardware the same wrapper compiles the
kernel natively."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rio_tpu.ops.pallas_sinkhorn import fused_iteration, pallas_sinkhorn
from rio_tpu.ops.sinkhorn import plan_rounded_assign, sinkhorn


def _problem(key, n, m, dead_nodes=0, padded_rows=0):
    k1, k2, k3 = jax.random.split(key, 3)
    cost = jax.random.uniform(k1, (n, m), jnp.float32)
    mass = jax.random.uniform(k2, (n,), jnp.float32) + 0.1
    if padded_rows:
        mass = mass.at[-padded_rows:].set(0.0)
    cap = jax.random.uniform(k3, (m,), jnp.float32) + 0.5
    if dead_nodes:
        cap = cap.at[:dead_nodes].set(0.0)
    return cost, mass, cap


@pytest.mark.parametrize("n,m,block", [(64, 128, 8), (96, 130, 32), (40, 100, 16)])
def test_pallas_matches_xla_sinkhorn(n, m, block):
    cost, mass, cap = _problem(jax.random.PRNGKey(0), n, m)
    ref = sinkhorn(cost, mass, cap, eps=0.08, n_iters=25)
    out = pallas_sinkhorn(
        cost, mass, cap, eps=0.08, n_iters=25, block_rows=block, interpret=True
    )
    np.testing.assert_allclose(out.f, ref.f, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(out.g, ref.g, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(float(out.err), float(ref.err), atol=1e-3)


def test_pallas_handles_dead_nodes_and_padding_rows():
    cost, mass, cap = _problem(
        jax.random.PRNGKey(1), 48, 96, dead_nodes=3, padded_rows=5
    )
    ref = sinkhorn(cost, mass, cap, eps=0.05, n_iters=30)
    out = pallas_sinkhorn(
        cost, mass, cap, eps=0.05, n_iters=30, block_rows=16, interpret=True
    )
    # Dead nodes end with -inf potential in both implementations.
    assert np.all(np.isneginf(np.asarray(out.g[:3])))
    np.testing.assert_allclose(
        np.asarray(out.g[3:]), np.asarray(ref.g[3:]), rtol=1e-4, atol=1e-4
    )
    # Padding rows carry -inf f.
    assert np.all(np.isneginf(np.asarray(out.f[-5:])))
    live_f = np.asarray(out.f[:-5])
    np.testing.assert_allclose(live_f, np.asarray(ref.f[:-5]), rtol=1e-4, atol=1e-4)
    # The downstream rounding consumes the potentials identically.
    a1 = plan_rounded_assign(cost, out.f, out.g, 0.05)
    a2 = plan_rounded_assign(cost, ref.f, ref.g, 0.05)
    assert np.array_equal(np.asarray(a1), np.asarray(a2))


def test_fused_iteration_single_step_math():
    """One fused step == one hand-rolled f-then-g update."""
    n, m, eps = 32, 128, 0.07
    key = jax.random.PRNGKey(2)
    cost = jax.random.uniform(key, (n, m), jnp.float32)
    log_a = jnp.log(jnp.full((n,), 1.0 / n))
    log_b = jnp.log(jnp.full((m,), 1.0 / m))
    g_prev = jax.random.normal(jax.random.PRNGKey(3), (m,)) * 0.01

    f, g = fused_iteration(
        cost, log_a, log_b, g_prev, jnp.float32(eps), block_rows=8, interpret=True
    )
    f_ref = eps * (log_a - jax.nn.logsumexp((g_prev[None, :] - cost) / eps, axis=1))
    g_ref = eps * (log_b - jax.nn.logsumexp((f_ref[:, None] - cost) / eps, axis=0))
    np.testing.assert_allclose(np.asarray(f), np.asarray(f_ref), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-5, atol=1e-5)
