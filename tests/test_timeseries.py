"""Gauge time-series ring + HealthWatch trend rules (ISSUE 11 tentpole).

Pure host-side units: the ring's overwrite/window/projection contract,
the wire row's tolerant decode, the shared trend helpers, and the
HealthWatch rule kinds (rising / falling / delta / drift) with journal
fire, cooldown, and exemplar-trace attach.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from rio_tpu.health import HealthAlert, HealthWatch, TrendRule, default_rules
from rio_tpu.journal import HEALTH, Journal
from rio_tpu.timeseries import (
    GaugeSeries,
    SeriesSample,
    falling_streak,
    merge_series,
    rising_streak,
    series_values,
    trend_arrow,
)

# ---------------------------------------------------------------------------
# GaugeSeries ring
# ---------------------------------------------------------------------------


def test_ring_overwrites_oldest_and_counts_dropped():
    s = GaugeSeries(capacity=4, node="n1")
    for i in range(6):
        s.sample({"g": float(i)})
    assert s.sampled == 6
    assert len(s) == 4
    assert s.dropped == 2
    window = s.window()
    assert [x.seq for x in window] == [3, 4, 5, 6]
    assert all(x.node == "n1" for x in window)
    # seq stays gap-free and monotonic across overwrite.
    assert [x.gauges["g"] for x in window] == [2.0, 3.0, 4.0, 5.0]


def test_window_projection_since_seq_and_limit():
    s = GaugeSeries(capacity=16)
    for i in range(8):
        s.sample(
            {
                "rio.load.req_rate": float(i),
                "rio.load.sheds": 0.0,
                "rio.handler.Svc.Get.p99_ms": 1.0 + i,
                "other": 9.0,
            }
        )
    # Exact name + prefix (trailing ".") projection.
    win = s.window(names=["rio.load.req_rate", "rio.handler."])
    assert len(win) == 8
    assert set(win[-1].gauges) == {
        "rio.load.req_rate",
        "rio.handler.Svc.Get.p99_ms",
    }
    # since_seq is exclusive and resumable.
    assert [x.seq for x in s.window(since_seq=5)] == [6, 7, 8]
    # limit keeps the NEWEST samples (a tail, not a head).
    assert [x.seq for x in s.window(limit=3)] == [6, 7, 8]
    assert [x.seq for x in s.window(since_seq=2, limit=2)] == [7, 8]


def test_tick_is_rate_limited_by_interval():
    s = GaugeSeries(capacity=8, interval=3600.0)
    assert s.tick(lambda: {"g": 1.0}) is not None
    # Second tick inside the interval records nothing (and must not even
    # evaluate the read callback's result into the ring).
    assert s.tick(lambda: {"g": 2.0}) is None
    assert s.sampled == 1


def test_sample_row_round_trip_and_tolerant_decode():
    s = SeriesSample(seq=7, wall_ts=123.5, mono_ts=9.25, node="a:1",
                     gauges={"g": 2.0})
    assert SeriesSample.from_row(s.to_row()) == s
    # Short legacy row: missing trailing fields default.
    short = SeriesSample.from_row([3, 11.0])
    assert (short.seq, short.wall_ts, short.node, short.gauges) == (
        3, 11.0, "", {})
    # A newer sender's extra trailing fields are ignored.
    extended = SeriesSample.from_row(s.to_row() + ["future", {"x": 1}])
    assert extended == s


def test_merge_series_orders_by_wall_clock_then_node():
    a = [SeriesSample(1, 10.0, 0, "a", {}), SeriesSample(2, 30.0, 0, "a", {})]
    b = [SeriesSample(1, 20.0, 0, "b", {}), SeriesSample(2, 30.0, 0, "b", {})]
    merged = merge_series([a, b])
    assert [(s.node, s.seq) for s in merged] == [
        ("a", 1), ("b", 1), ("a", 2), ("b", 2)]


def test_series_gauges_scrape_keys():
    s = GaugeSeries(capacity=4)
    s.sample({})
    g = s.gauges()
    assert g["rio.series.samples"] == 1.0
    assert g["rio.series.dropped"] == 0.0
    assert g["rio.series.ring_occupancy"] == 1.0
    assert g["rio.series.ring_capacity"] == 4.0


# ---------------------------------------------------------------------------
# trend helpers
# ---------------------------------------------------------------------------


def test_rising_streak_and_min_delta():
    assert rising_streak([1, 2, 3, 4]) == 3
    assert rising_streak([5, 1, 2, 3]) == 2
    assert rising_streak([3, 2, 1]) == 0
    assert rising_streak([1]) == 0
    # The jitter floor: +0.4 steps don't count against min_delta=0.5.
    assert rising_streak([1.0, 1.4, 1.8], min_delta=0.5) == 0
    assert rising_streak([1.0, 2.0, 3.1], min_delta=0.5) == 2


def test_falling_streak_and_min_delta():
    # Mirror of the rising cases: the streak ends at the newest value.
    assert falling_streak([4, 3, 2, 1]) == 3
    assert falling_streak([1, 5, 4, 3]) == 2
    assert falling_streak([1, 2, 3]) == 0
    assert falling_streak([1]) == 0
    # The jitter floor: -0.4 steps don't count against min_delta=0.5.
    assert falling_streak([1.8, 1.4, 1.0], min_delta=0.5) == 0
    assert falling_streak([3.1, 2.0, 1.0], min_delta=0.5) == 2


def test_trend_arrow_dead_band():
    assert trend_arrow([10, 10, 10, 10.2]) == "→"  # within ±5% of mean
    assert trend_arrow([10, 10, 10, 12]) == "↑"
    assert trend_arrow([10, 10, 10, 8]) == "↓"
    assert trend_arrow([5.0]) == "→"
    assert trend_arrow([]) == "→"


def test_series_values_skips_samples_missing_the_gauge():
    samples = [
        SeriesSample(1, 1.0, 0, "n", {"a": 1.0}),
        SeriesSample(2, 2.0, 0, "n", {"b": 5.0}),
        SeriesSample(3, 3.0, 0, "n", {"a": 2.0}),
    ]
    assert series_values(samples, "a") == [1.0, 2.0]


# ---------------------------------------------------------------------------
# HealthWatch
# ---------------------------------------------------------------------------


def _fed_series(values_by_gauge: dict[str, list[float]]) -> GaugeSeries:
    """A ring pre-fed column-wise: one sample per index across gauges."""
    n = max(len(v) for v in values_by_gauge.values())
    s = GaugeSeries(capacity=max(8, n), node="n1")
    for i in range(n):
        s.sample({k: v[i] for k, v in values_by_gauge.items() if i < len(v)})
    return s


def test_rising_rule_fires_and_journals_health_event():
    series = _fed_series({"rio.load.loop_lag_ms": [1.0, 2.0, 3.0, 4.0]})
    journal = Journal(node="n1")
    hw = HealthWatch(
        series,
        journal=journal,
        rules=[TrendRule(name="lag", gauge="rio.load.loop_lag_ms",
                         kind="rising", windows=3, min_delta=0.5)],
    )
    active = hw.tick()
    assert [a.rule for a in active] == ["lag"]
    assert active[0].gauge == "rio.load.loop_lag_ms"
    assert active[0].value == 4.0
    assert hw.fired_total == 1
    events = [e for e in journal.events() if e.kind == HEALTH]
    assert len(events) == 1
    assert events[0].key == "lag"
    assert events[0].attrs["gauge"] == "rio.load.loop_lag_ms"
    assert events[0].attrs["windows"] == 3
    # Scrape + meta surfaces agree.
    g = hw.gauges()
    assert g["rio.health.alerts_active"] == 1.0
    assert g["rio.health.alert.lag"] == 1.0
    assert hw.meta() == {"alerts": ["lag:rio.load.loop_lag_ms"]}


def test_rising_rule_respects_jitter_floor():
    series = _fed_series({"g": [1.0, 1.1, 1.2, 1.3]})  # rising, but tiny
    hw = HealthWatch(series, rules=[
        TrendRule(name="r", gauge="g", kind="rising", windows=3,
                  min_delta=0.5)])
    assert hw.tick() == []
    assert hw.gauges()["rio.health.alert.r"] == 0.0


def test_falling_rule_fires_and_journals_health_event():
    # Mirror of the rising case: "load has been dropping for K windows"
    # (the scale-in trigger shape).
    series = _fed_series({"rio.cluster.loop_lag_mean_ms": [4.0, 3.0, 2.0, 1.0]})
    journal = Journal(node="n1")
    hw = HealthWatch(
        series,
        journal=journal,
        rules=[TrendRule(name="load_falling",
                         gauge="rio.cluster.loop_lag_mean_ms",
                         kind="falling", windows=3, min_delta=0.5)],
    )
    active = hw.tick()
    assert [a.rule for a in active] == ["load_falling"]
    assert active[0].gauge == "rio.cluster.loop_lag_mean_ms"
    assert active[0].value == 1.0
    events = [e for e in journal.events() if e.kind == HEALTH]
    assert len(events) == 1
    assert events[0].key == "load_falling"
    assert events[0].attrs["windows"] == 3


def test_falling_rule_respects_jitter_floor():
    series = _fed_series({"g": [1.3, 1.2, 1.1, 1.0]})  # falling, but tiny
    hw = HealthWatch(series, rules=[
        TrendRule(name="f", gauge="g", kind="falling", windows=3,
                  min_delta=0.5)])
    assert hw.tick() == []
    assert hw.gauges()["rio.health.alert.f"] == 0.0


def test_falling_rule_ignores_rising_series():
    series = _fed_series({"g": [1.0, 2.0, 3.0, 4.0]})
    hw = HealthWatch(series, rules=[
        TrendRule(name="f", gauge="g", kind="falling", windows=3)])
    assert hw.tick() == []


def test_delta_rule_fires_on_counter_growth():
    series = _fed_series({"rio.load.sheds": [0.0, 0.0, 2.0, 5.0]})
    hw = HealthWatch(series, rules=[
        TrendRule(name="sheds", gauge="rio.load.sheds", kind="delta",
                  windows=3)])
    active = hw.tick()
    assert [a.rule for a in active] == ["sheds"]
    assert "+5" in active[0].detail


def test_drift_rule_needs_factor_and_absolute_floor():
    # 3x the mean but under the 5-unit absolute floor: no fire.
    quiet = _fed_series({"g": [1.0, 1.0, 1.0, 3.0]})
    hw = HealthWatch(quiet, rules=[
        TrendRule(name="d", gauge="g", kind="drift", windows=3, factor=2.0,
                  min_delta=5.0)])
    assert hw.tick() == []
    # Over both the factor and the floor: fires.
    loud = _fed_series({"g": [10.0, 10.0, 10.0, 40.0]})
    hw = HealthWatch(loud, rules=[
        TrendRule(name="d", gauge="g", kind="drift", windows=3, factor=2.0,
                  min_delta=5.0)])
    active = hw.tick()
    assert [a.rule for a in active] == ["d"]


def test_unknown_rule_kind_is_a_noop():
    series = _fed_series({"g": [1.0, 2.0, 3.0, 4.0]})
    hw = HealthWatch(series, rules=[
        TrendRule(name="x", gauge="g", kind="quantum")])
    assert hw.tick() == []


def test_cooldown_rate_limits_journal_refires():
    series = _fed_series({"g": [1.0, 2.0, 3.0, 4.0]})
    journal = Journal(node="n1")
    hw = HealthWatch(series, journal=journal, rules=[
        TrendRule(name="r", gauge="g", kind="rising", windows=3,
                  cooldown=3)])
    hw.tick()
    assert hw.fired_total == 1
    # Condition persists over the next two samples: still active, no refire.
    series.sample({"g": 5.0})
    series.sample({"g": 6.0})
    assert len(hw.tick()) == 1
    assert hw.fired_total == 1
    # A third sample clears the cooldown window: refires.
    series.sample({"g": 7.0})
    hw.tick()
    assert hw.fired_total == 2
    assert len([e for e in journal.events() if e.kind == HEALTH]) == 2


def test_handler_latency_alert_attaches_exemplar_trace():
    series = _fed_series(
        {"rio.handler.Svc.Get.p99_ms": [1.0, 2.0, 3.0, 4.0]})
    journal = Journal(node="n1")
    hw = HealthWatch(
        series,
        journal=journal,
        exemplars=lambda: {"Svc.Get": "0af7651916cd43dd8448eb211c80319c"},
        rules=[TrendRule(name="p99", gauge="rio.handler.*.p99_ms",
                         kind="rising", windows=3, min_delta=0.5)],
    )
    active = hw.tick()
    assert active[0].trace_id == "0af7651916cd43dd8448eb211c80319c"
    ev = [e for e in journal.events() if e.kind == HEALTH][0]
    assert ev.trace_id == "0af7651916cd43dd8448eb211c80319c"


def test_exemplar_lookup_failure_never_blocks_the_alert():
    series = _fed_series(
        {"rio.handler.Svc.Get.p99_ms": [1.0, 2.0, 3.0, 4.0]})

    def boom():
        raise RuntimeError("registry gone")

    hw = HealthWatch(series, exemplars=boom, rules=[
        TrendRule(name="p99", gauge="rio.handler.*.p99_ms", kind="rising",
                  windows=3, min_delta=0.5)])
    active = hw.tick()
    assert len(active) == 1 and active[0].trace_id == ""


def test_too_few_samples_keeps_watch_quiet():
    series = GaugeSeries(capacity=8)
    series.sample({"g": 1.0})
    hw = HealthWatch(series)
    assert hw.tick() == []
    assert hw.active == []


def test_default_journal_dropped_rule_catches_ring_overflow():
    """Regression (ISSUE 11 satellite): a journal ring that starts dropping
    events — the flight recorder overwriting unread history — must raise
    the stock ``journal_dropped`` alarm from its own gauge feed."""
    journal = Journal(capacity=2, node="n1")
    series = GaugeSeries(capacity=16, node="n1")
    hw = HealthWatch(series, journal=journal, rules=default_rules())

    def snapshot():
        series.sample(journal.gauges())
        return hw.tick()

    journal.record("member_up", "n1")
    assert snapshot() == []  # single sample: quiet
    assert snapshot() == []  # flat dropped count: quiet
    for i in range(4):  # capacity 2 → these overwrite, dropped grows
        journal.record("member_up", f"n{i}")
    active = snapshot()
    assert "journal_dropped" in {a.rule for a in active}
    fired = [e for e in journal.events() if e.kind == HEALTH]
    assert fired and fired[0].key == "journal_dropped"
    assert fired[0].attrs["gauge"] == "rio.journal.dropped"


def test_default_rules_cover_the_stock_alarm_set():
    names = {r.name for r in default_rules()}
    assert names == {
        "p99_rising", "loop_lag_rising", "journal_dropped", "shed_rate",
        "residual_diverging", "storage_errors", "solve_ms_drift",
        "cluster_load_falling", "cross_node_bytes_rising",
        "qos_shed_rising", "deadline_exceeded_rising",
    }
    kinds = {r.name: r.kind for r in default_rules()}
    assert kinds["journal_dropped"] == "delta"
    assert kinds["storage_errors"] == "delta"
    assert kinds["qos_shed_rising"] == "delta"
    assert kinds["deadline_exceeded_rising"] == "delta"
    assert kinds["solve_ms_drift"] == "drift"
    assert kinds["cross_node_bytes_rising"] == "rising"
    assert kinds["cluster_load_falling"] == "falling"


def test_health_alert_defaults():
    a = HealthAlert(rule="r", gauge="g", value=1.0)
    assert a.trace_id == "" and a.seq == 0 and a.detail == ""
