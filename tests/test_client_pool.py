"""Client pool: bounded size, reuse, discard, close (reference client/pool.rs)."""

import asyncio

import pytest

from rio_tpu import AppData, Registry, ServiceObject, handler, message
from rio_tpu.client.pool import ClientPool

from .server_utils import Cluster, run_integration_test


@message
class PoolPing:
    pass


@message
class PoolPong:
    n: int = 0


class PoolSvc(ServiceObject):
    def __init__(self):
        self.n = 0

    @handler
    async def ping(self, msg: PoolPing, ctx: AppData) -> PoolPong:
        self.n += 1
        await asyncio.sleep(0.01)
        return PoolPong(n=self.n)


def build_registry() -> Registry:
    r = Registry()
    r.add_type(PoolSvc)
    return r


def test_pool_bounds_and_reuses_clients():
    async def body(cluster: Cluster):
        pool = ClientPool(cluster.members, max_size=3)

        async def one(i: int):
            async with pool.client() as c:
                assert pool.size <= 3
                return await c.send(PoolSvc, f"p{i % 5}", PoolPing(), returns=PoolPong)

        outs = await asyncio.gather(*[one(i) for i in range(30)])
        assert len(outs) == 30
        assert pool.size <= 3  # never exceeded the bound
        assert pool.idle == pool.size  # everything returned
        pool.close()
        assert pool.size == 0

    asyncio.run(run_integration_test(body, registry_builder=build_registry, num_servers=2))


def test_pool_discard_replaces_client():
    async def body(cluster: Cluster):
        pool = ClientPool(cluster.members, max_size=2)
        async with pool.client() as c:
            await c.send(PoolSvc, "d", PoolPing(), returns=PoolPong)
            c.discard()
        assert pool.size == 0  # the discarded client is gone
        async with pool.client() as c2:
            out = await c2.send(PoolSvc, "d", PoolPing(), returns=PoolPong)
            assert out.n == 2
        pool.close()

    asyncio.run(run_integration_test(body, registry_builder=build_registry, num_servers=2))


def test_pool_waiters_queue_until_release():
    async def body(cluster: Cluster):
        pool = ClientPool(cluster.members, max_size=1)
        order: list[int] = []

        async def task(i: int):
            async with pool.client() as c:
                order.append(i)
                await c.send(PoolSvc, "w", PoolPing(), returns=PoolPong)

        await asyncio.gather(task(1), task(2), task(3))
        assert sorted(order) == [1, 2, 3]
        assert pool.size == 1
        pool.close()

    asyncio.run(run_integration_test(body, registry_builder=build_registry, num_servers=2))


def test_pool_closed_rejects_acquire():
    async def run():
        from rio_tpu import LocalStorage

        pool = ClientPool(LocalStorage(), max_size=2)
        pool.close()
        with pytest.raises(RuntimeError):
            async with pool.client():
                pass

    asyncio.run(run())
