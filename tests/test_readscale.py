"""Read scale-out: @readonly marker, bounded-staleness standby serving,
busy-shed seat hints + client diversion, dynamic replication factor, and
the defensive decode surfaces the subsystem leans on.

The staleness CONTRACT under test: a standby answers a readonly request
only while its replica is inside the configured lag/age bounds; outside
them it transparently proxies to the primary — never an error, never an
answer beyond the bound.
"""

import asyncio
import random
import time

import pytest

from rio_tpu import (
    AppData,
    Client,
    LocalObjectPlacement,
    LocalStorage,
    ReadScaleConfig,
    ReadScaleManager,
    Registry,
    ServiceObject,
    handler,
    message,
    readonly,
)
from rio_tpu import codec
from rio_tpu.cluster.storage import Member
from rio_tpu.commands import ServerInfo
from rio_tpu.load import LoadThresholds
from rio_tpu.migration import ReplicaAppend
from rio_tpu.object_placement import ObjectPlacementItem, sanitize_standby_row
from rio_tpu.protocol import RequestEnvelope, decode_response, encode_request_frame
from rio_tpu.readscale import decode_seat_hint
from rio_tpu.registry import (
    READONLY_MESSAGES,
    ObjectId,
    is_readonly_message,
    register_readonly,
    type_id,
)
from rio_tpu.replication import (
    ReplicaAck,
    ReplicaFreshness,
    ReplicationConfig,
    ReplicationManager,
)
from rio_tpu.utils import DecorrelatedJitter

from .server_utils import Cluster, run_integration_test


@message
class CBump:
    amount: int = 1


@message
class CRead:
    pass


@message
class CSnap:
    version: int = 0
    address: str = ""


class Celebrity(ServiceObject):
    """Replicated hot actor: write bumps a version, readonly read returns it."""

    __replicated__ = True

    def __init__(self):
        self.version = 0

    def __migrate_state__(self):
        return {"version": self.version}

    def __restore_state__(self, value):
        self.version = int(value["version"])

    @handler
    async def bump(self, msg: CBump, ctx: AppData) -> CSnap:
        self.version += msg.amount
        return CSnap(version=self.version, address=ctx.get(ServerInfo).address)

    @readonly
    @handler
    async def read(self, msg: CRead, ctx: AppData) -> CSnap:
        return CSnap(version=self.version, address=ctx.get(ServerInfo).address)


def build_registry() -> Registry:
    return Registry().add_type(Celebrity)


TNAME = type_id(Celebrity)


# ---------------------------------------------------------------------------
# @readonly marker
# ---------------------------------------------------------------------------


def test_readonly_marker_registers_through_add_type():
    r = build_registry()
    assert r.is_readonly(TNAME, type_id(CRead))
    assert not r.is_readonly(TNAME, type_id(CBump))
    spec = r.handler_spec(TNAME, type_id(CRead))
    assert spec is not None and spec.readonly
    # add_type published into the process-global set clients route from.
    assert (TNAME, type_id(CRead)) in READONLY_MESSAGES
    assert is_readonly_message(TNAME, type_id(CRead))
    assert not is_readonly_message(TNAME, type_id(CBump))


def test_readonly_composes_with_handler_in_either_order():
    @message(name="readscale_test.Q")
    class Q:
        pass

    class A(ServiceObject):
        @handler
        @readonly
        async def under(self, msg: Q, ctx: AppData) -> int:
            return 0

    class B(ServiceObject):
        @readonly
        @handler
        async def over(self, msg: Q, ctx: AppData) -> int:
            return 0

    for cls in (A, B):
        register_readonly(cls)
        assert is_readonly_message(type_id(cls), type_id(Q))


# ---------------------------------------------------------------------------
# Defensive decode: seat hints and standby rows
# ---------------------------------------------------------------------------


def test_decode_seat_hint_tolerates_garbage():
    assert decode_seat_hint(b"") == []
    assert decode_seat_hint(b"\xff\xfe not msgpack") == []
    assert decode_seat_hint(codec.serialize(42)) == []
    assert decode_seat_hint(codec.serialize({"not": "a list"})) == []
    wire = codec.serialize(["ok:1", "noport", 7, None, "h:x", "b:22"])
    assert decode_seat_hint(wire) == ["ok:1", "b:22"]


def test_sanitize_standby_row_contract():
    assert sanitize_standby_row(["a:1", "b:2"], 3) == (["a:1", "b:2"], 3)
    # Garbage epoch poisons the fence: whole row degrades to "no standbys".
    assert sanitize_standby_row(["a:1"], "zz") == ([], 0)
    assert sanitize_standby_row(["a:1"], None) == ([], 0)
    assert sanitize_standby_row(["a:1"], -4) == ([], 0)
    # Malformed members are filtered; the rest of the set survives.
    assert sanitize_standby_row(["a:1", "noport", 9, b"c:3"], "2") == (
        ["a:1", "c:3"],
        2,
    )
    assert sanitize_standby_row("a:1,b:2", 1) == ([], 1)  # wrong container


@pytest.mark.asyncio
async def test_garbage_standby_rows_decode_as_no_standbys_local_and_jax(tmp_path):
    """Every directory backend must degrade a legacy/garbage standby row to
    ([], 0)-style answers on the read path — never raise."""
    from rio_tpu.object_placement import LocalObjectPlacement
    from rio_tpu.object_placement.jax_placement import JaxObjectPlacement
    from rio_tpu.object_placement.persistent import PersistentJaxObjectPlacement
    from rio_tpu.object_placement.sqlite import SqliteObjectPlacement

    oid = ObjectId("Svc", "g1")

    local = LocalObjectPlacement()
    local._standbys[str(oid)] = (["ok:1", "garbage", 7], "not-an-epoch")
    assert await local.standbys(oid) == ([], 0)
    local._standbys[str(oid)] = (["ok:1", "garbage"], 2)
    assert await local.standbys(oid) == (["ok:1"], 2)

    jx = JaxObjectPlacement()
    await jx.prepare()
    jx._standby_rows[str(oid)] = ([b"\xff\xfe", "ok:1"], True)
    assert await jx.standbys(oid) == (["ok:1"], 1)

    pj = PersistentJaxObjectPlacement(
        SqliteObjectPlacement(str(tmp_path / "pj.db"))
    )
    await pj.prepare()
    pj._standby_rows[str(oid)] = (object(), object())
    assert await pj.standbys(oid) == ([], 0)

    sq = SqliteObjectPlacement(str(tmp_path / "p.db"))
    await sq.prepare()
    # A legacy writer's raw row: epoch TEXT affinity, malformed addresses.
    await sq.db.execute(
        "INSERT INTO object_standby (struct_name, object_id, standbys, epoch) "
        "VALUES (?,?,?,?)",
        "Svc", "g1", "ok:1,,broken", "oops",
    )
    assert await sq.standbys(oid) == ([], 0)
    await sq.db.execute(
        "UPDATE object_standby SET epoch=3 WHERE struct_name=? AND object_id=?",
        "Svc", "g1",
    )
    assert await sq.standbys(oid) == (["ok:1"], 3)


@pytest.mark.asyncio
async def test_garbage_standby_rows_decode_as_no_standbys_redis_and_postgres():
    from rio_tpu.object_placement.postgres import PostgresObjectPlacement
    from rio_tpu.object_placement.redis import RedisObjectPlacement
    from rio_tpu.utils.resp import RedisClient

    from tests import fake_pg
    from tests.fake_redis import FakeRedisServer

    oid = ObjectId("Svc", "g1")

    server = await FakeRedisServer().start()
    try:
        client = RedisClient("127.0.0.1", server.port)
        rp = RedisObjectPlacement(client, key_prefix="t_rs")
        for raw in (b"garbage-no-bar", b"zz|ok:1", b"\xff\xfe\xfd", b"-3|ok:1"):
            await client.execute("SET", rp._standby_key(str(oid)), raw)
            assert await rp.standbys(oid) == ([], 0)
        await client.execute("SET", rp._standby_key(str(oid)), b"2|ok:1,junk")
        assert await rp.standbys(oid) == (["ok:1"], 2)
        client.close()
    finally:
        await server.stop()

    fake_pg.install()
    fake_pg.reset()
    pg = PostgresObjectPlacement("postgresql://fake-pg/readscale")
    await pg.prepare()
    await pg.db.execute(
        "INSERT INTO object_standby (struct_name, object_id, standbys, epoch) "
        "VALUES (?,?,?,?)",
        "Svc", "g1", "junk,ok:1", "NaN-epoch",
    )
    assert await pg.standbys(oid) == ([], 0)


# ---------------------------------------------------------------------------
# DecorrelatedJitter
# ---------------------------------------------------------------------------


def test_decorrelated_jitter_bounds_and_decorrelation():
    j = DecorrelatedJitter(base=1e-3, cap=0.5)
    prev = 1e-3
    for _ in range(200):
        d = j.next()
        assert 1e-3 <= d <= 0.5
        assert d <= max(prev * 3, 0.5)
        prev = d
    # Two requests shedding at the same instant must not march in lockstep:
    # independent instances draw different sequences.
    random.seed(1234)
    a = [DecorrelatedJitter(base=1e-3, cap=2.0).next() for _ in range(8)]
    random.seed(1234)
    j1, j2 = DecorrelatedJitter(base=1e-3, cap=2.0), DecorrelatedJitter(
        base=1e-3, cap=2.0
    )
    seq1 = [j1.next() for _ in range(8)]
    seq2 = [j2.next() for _ in range(8)]
    assert seq1 != seq2
    assert a  # seeded draw above exercised the module-level RNG path


# ---------------------------------------------------------------------------
# ReplicaFreshness + refresh pings
# ---------------------------------------------------------------------------


def test_replica_freshness_lag_and_age():
    f = ReplicaFreshness(epoch=2, seq=5, head_seq=9, recv_mono=time.monotonic())
    assert f.lag_seq == 4
    assert f.age_s() < 0.5
    assert f.age_s(f.recv_mono + 3.0) == pytest.approx(3.0)
    # head_seq behind seq (legacy frames) never yields negative lag.
    g = ReplicaFreshness(seq=5, head_seq=0)
    assert g.lag_seq == 0


def _mgr(address="10.0.0.1:1", placement=None, members=None) -> ReplicationManager:
    return ReplicationManager(
        address=address,
        registry=build_registry(),
        placement=placement or LocalObjectPlacement(),
        members_storage=members or LocalStorage(),
        app_data=AppData(),
    )


def test_apply_append_refresh_ping_updates_freshness_or_nacks():
    mgr = _mgr()
    key = (TNAME, "c1")

    def append(**kw):
        return mgr.apply_append(
            ReplicaAppend(type_name=TNAME, object_id="c1", **kw)
        )

    # Ping with no replica held: nack (primary must full-re-ship).
    nack = append(epoch=1, seq=3, head_seq=3, refresh=True)
    assert not nack.ok and "refresh" in nack.detail
    assert mgr.stats.append_nacks == 1

    ok = append(epoch=1, seq=3, payload=b"v3", head_seq=3)
    assert ok.ok
    before = mgr.replica_freshness(key)
    assert before is not None and before.lag_seq == 0

    # Ping for a moved head: freshness (and lag) track it, store untouched.
    ping = append(epoch=1, seq=5, head_seq=5, refresh=True)
    assert ping.ok
    after = mgr.replica_freshness(key)
    assert after is not None and after.recv_mono >= before.recv_mono
    assert mgr.replica_entry(key) == (b"v3", 1, 3)

    # Ping from a different epoch (promotion happened): nack with ours.
    cross = append(epoch=2, seq=5, head_seq=5, refresh=True)
    assert not cross.ok and cross.epoch == 1


@pytest.mark.asyncio
async def test_refresh_nack_reopens_key_for_full_reship():
    members = LocalStorage()
    await members.push(Member(ip="10.0.0.1", port=1, active=True))
    await members.push(Member(ip="10.0.0.2", port=2, active=True))
    placement = LocalObjectPlacement()
    mgr = _mgr(placement=placement, members=members)
    oid = ObjectId(TNAME, "c1")
    key = (TNAME, "c1")
    await placement.update(ObjectPlacementItem(oid, "10.0.0.1:1"))
    await placement.set_standbys(oid, ["10.0.0.2:2"])
    mgr._last_shipped[key] = b"v3"
    mgr._seq[key] = 3

    sent: list[ReplicaAppend] = []
    acks = [ReplicaAck(ok=True, epoch=0)]

    async def fake_append(addr, msg):
        sent.append(msg)
        return acks[0]

    mgr._append_to = fake_append

    await mgr.refresh_standbys(oid)
    assert mgr.stats.refreshes == 1 and mgr.stats.refresh_nacks == 0
    assert sent[-1].refresh and sent[-1].payload == b""
    assert sent[-1].seq == 3 and sent[-1].head_seq == 3
    assert key in mgr._last_shipped

    # Standby lost the replica (restart): nacked ping reopens the key so
    # the next anti-entropy round re-ships the full payload.
    acks[0] = ReplicaAck(ok=False, detail="no replica for refresh")
    await mgr.refresh_standbys(oid)
    assert mgr.stats.refresh_nacks == 1
    assert key not in mgr._last_shipped and key in mgr._dirty


# ---------------------------------------------------------------------------
# Dynamic replication factor (deterministic, no cluster)
# ---------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_dynamic_k_ramps_to_kmax_and_decays_to_kmin_epoch_fenced():
    members = LocalStorage()
    for i in range(1, 6):
        await members.push(Member(ip="10.0.0.%d" % i, port=i, active=True))
    placement = LocalObjectPlacement()
    registry = build_registry()
    self_addr = "10.0.0.1:1"
    repl = ReplicationManager(
        address=self_addr,
        registry=registry,
        placement=placement,
        members_storage=members,
        app_data=AppData(),
        config=ReplicationConfig(k=1),
    )
    mgr = ReadScaleManager(
        address=self_addr,
        registry=registry,
        replication=repl,
        placement=placement,
        members_storage=members,
        app_data=AppData(),
        config=ReadScaleConfig(hot_rate=100.0, k_min=1, k_max=3),
    )
    oid = ObjectId(TNAME, "hot")
    key = (TNAME, "hot")
    registry.insert(TNAME, "hot", registry.new_from_type(TNAME, "hot"))
    await placement.update(ObjectPlacementItem(oid, self_addr))

    async def seats():
        held, epoch = await placement.standbys(oid)
        assert self_addr not in held, "primary/standby co-location"
        return held, epoch

    # Cold key: baseline k, one transition to seat the initial standby set
    # never fires (rate 0 -> target == current k).
    assert await mgr.hotness_tick({str(oid): 0.0}) == 0
    assert repl.replica_k(key) == 1

    # Rate storm: ramp straight to k_max, seats topped up, epoch untouched.
    assert await mgr.hotness_tick({str(oid): 250.0}) == 1
    assert repl.replica_k(key) == 3 and mgr.stats.k_raises == 1
    held, epoch = await seats()
    assert len(held) == 3 and len(set(held)) == 3 and epoch == 0

    # Same storm again: steady state, no churn.
    assert await mgr.hotness_tick({str(oid): 260.0}) == 0

    # Cooling: one seat per tick, only under the hysteresis margin.
    assert await mgr.hotness_tick({str(oid): 40.0}) == 1
    assert repl.replica_k(key) == 2 and mgr.stats.k_lowers == 1
    held, epoch = await seats()
    assert len(held) == 2 and epoch == 0

    assert await mgr.hotness_tick({str(oid): 10.0}) == 1
    assert repl.replica_k(key) == 1 and mgr.stats.k_lowers == 2
    held, epoch = await seats()
    assert len(held) == 1 and epoch == 0

    # Floor: never below k_min, no transition churn at idle.
    assert await mgr.hotness_tick({str(oid): 0.0}) == 0
    assert repl.replica_k(key) == 1
    assert mgr.gauges()[f"rio.read_scale.replica_k.{TNAME}.hot"] == 1.0


# ---------------------------------------------------------------------------
# Live cluster: standby serves fresh, forwards stale, sheds with seats
# ---------------------------------------------------------------------------


async def _raw_read(address: str, object_id: str):
    """One readonly request over a raw framed connection to ``address``."""
    from rio_tpu.client import _ServerConns

    pool = _ServerConns(address, 1, 2.0)
    try:
        req = RequestEnvelope(
            TNAME, object_id, type_id(CRead), codec.serialize(CRead())
        )
        conn = await pool.acquire()
        try:
            raw = await conn.roundtrip(encode_request_frame(req))
        finally:
            pool.release(conn, reuse=True)
        resp = decode_response(raw)
        assert resp.is_ok, resp.error
        return codec.deserialize(resp.body, CSnap)
    finally:
        pool.close()


def test_standby_serves_fresh_read_and_forwards_stale():
    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            out = await client.send(Celebrity, "c1", CBump(amount=1), returns=CSnap)
            primary_addr = out.address
            held, _ = await cluster.placement.standbys(ObjectId(TNAME, "c1"))
            assert held and primary_addr not in held
            standby = next(
                s for s in cluster.servers if s.local_address == held[0]
            )
            key = (TNAME, "c1")
            assert standby.replication_manager.replica_entry(key) is not None

            # Fresh replica: the standby answers locally, never touching the
            # primary, and the answer reflects every acked write.
            snap = await _raw_read(standby.local_address, "c1")
            assert snap.version == 1
            assert snap.address == standby.local_address
            assert standby.read_scale_manager.stats.standby_reads == 1
            assert standby.read_scale_manager.stats.standby_forwards == 0

            # Age the replica past the bound: the SAME request now proxies
            # to the primary — an up-to-date answer, not an error.
            meta = standby.replication_manager._replica_meta[key]
            meta.recv_mono -= 60.0
            snap = await _raw_read(standby.local_address, "c1")
            assert snap.version == 1
            assert snap.address == primary_addr
            assert standby.read_scale_manager.stats.stale_refusals == 1
            assert standby.read_scale_manager.stats.standby_forwards == 1

            # A new acked write re-freshens the replica (ship-on-ack):
            # standby serving resumes at the new version.
            await client.send(Celebrity, "c1", CBump(amount=1), returns=CSnap)
            snap = await _raw_read(standby.local_address, "c1")
            assert snap.version == 2
            assert snap.address == standby.local_address
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=3,
            server_kwargs={
                "replication_config": ReplicationConfig(
                    k=1, anti_entropy_interval=0.2, seat_ttl=0.2
                ),
                "read_scale_config": ReadScaleConfig(max_staleness_s=5.0),
            },
        )
    )


def test_hot_primary_sheds_reads_to_seats_and_client_diverts():
    async def body(cluster: Cluster):
        client = cluster.client(read_scale=ReadScaleConfig())
        try:
            out = await client.send(Celebrity, "h1", CBump(amount=1), returns=CSnap)
            primary_addr = out.address
            primary = next(
                s for s in cluster.servers if s.local_address == primary_addr
            )
            held, _ = await cluster.placement.standbys(ObjectId(TNAME, "h1"))
            assert held and primary_addr not in held
            key = (TNAME, "h1")

            # Prime the primary's seat cache (shed_read is cache-only), then
            # make it shed everything.
            await client.send(Celebrity, "h1", CRead(), returns=CSnap)
            assert key in primary.replication_manager._seats
            primary.load_monitor.thresholds = LoadThresholds(max_inflight=-1)

            snap = await client.send(Celebrity, "h1", CRead(), returns=CSnap)
            # The shed named the standby seats; the client diverted there
            # and the standby served from its replica.
            assert snap.address in held
            assert snap.version == 1
            assert client.stats.busy_retries == 1
            assert client.stats.standby_routes >= 1
            assert primary.read_scale_manager.stats.read_sheds == 1
            # The primary row stays cached — it is still the write target.
            assert client._placement.get(key) == primary_addr

            # Later reads ride the cached seat hint straight to the standby
            # (no second busy bounce off the primary).
            routes = client.stats.standby_routes
            snap = await client.send(Celebrity, "h1", CRead(), returns=CSnap)
            assert snap.address in held
            assert client.stats.busy_retries == 1
            assert client.stats.standby_routes > routes

            # Writes are never diverted: they go to the primary and still
            # succeed (the generic shed skips activated objects).
            out = await client.send(Celebrity, "h1", CBump(amount=1), returns=CSnap)
            assert out.address == primary_addr and out.version == 2
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=3,
            server_kwargs={
                "replication_config": ReplicationConfig(
                    k=2, anti_entropy_interval=0.2, seat_ttl=60.0
                ),
                "read_scale_config": ReadScaleConfig(max_staleness_s=5.0),
            },
        )
    )


def test_many_concurrent_busy_clients_all_complete_with_jitter():
    """Regression for the decorrelated-jitter backoff: a whole fleet of
    clients shed at the same instant must drain once capacity returns —
    no lockstep retry storm starving a subset into RetryExhausted."""

    async def body(cluster: Cluster):
        from rio_tpu.utils.backoff import ExponentialBackoff

        for s in cluster.servers:
            s.load_monitor.thresholds = LoadThresholds(max_inflight=-1)

        clients = [
            cluster.client(backoff=ExponentialBackoff(initial=2e-3, cap=0.25))
            for _ in range(8)
        ]
        try:
            async def one(ci: int, ri: int):
                c = clients[ci]
                return await c.send(
                    Celebrity, f"m{ci}.{ri}", CBump(amount=1), returns=CSnap
                )

            tasks = [
                asyncio.create_task(one(ci, ri))
                for ci in range(len(clients))
                for ri in range(3)
            ]
            # Every request is busy-shed (nothing is activated while every
            # node refuses admission) ... until capacity "returns".
            await asyncio.sleep(0.1)
            for s in cluster.servers:
                s.load_monitor.thresholds = LoadThresholds()
            outs = await asyncio.gather(*tasks)
            assert all(o.version == 1 for o in outs)
            assert sum(c.stats.busy_retries for c in clients) > 0
        finally:
            for c in clients:
                c.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=2,
            server_kwargs={"replication_config": ReplicationConfig(k=1)},
        )
    )
