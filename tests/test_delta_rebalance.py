"""Incremental (delta) rebalance: residual-capacity solves over the
displaced set, warm-started from the previous plan's potentials.

The contract under test (ISSUE 8 / README "Incremental rebalance"):

- A churn event re-solves ONLY the displaced objects; undisplaced objects
  never move (``test_delta_moves_exactly_the_displaced_set``).
- The delta landing matches the integer fair quotas a full solve targets,
  so transport cost stays within ``delta_audit_ratio`` of the full-solve
  ideal (``test_delta_cost_parity_with_full_solve``).
- Every gate that routes an event back to the full pipeline works:
  displaced fraction over ``delta_threshold``, ``max_delta_solves``
  staleness bound, a tripped transport-cost audit, ``delta=False`` /
  ``delta=True`` overrides, and the zero-schedulable-capacity outage mode.
- The epoch-discard consistency check covers the delta path exactly like
  the full path: a directory that changed under the solve discards it.
- Warm-started solver calls are semantically equivalent to cold ones
  (log-domain reference parity, including the wide-cost-range per-row
  gauge regime), so warm-starting is purely a convergence accelerator.
"""

import asyncio
import time

import jax.numpy as jnp
import numpy as np
import pytest

from rio_tpu import ObjectId
from rio_tpu.object_placement.jax_placement import JaxObjectPlacement
from rio_tpu.ops import integer_fair_quotas, residual_capacity_assign
from rio_tpu.ops.scaling import scaling_sinkhorn
from rio_tpu.ops.sinkhorn import sinkhorn


class _Member:
    def __init__(self, address: str, active: bool = True) -> None:
        self.address = address
        self.active = active


def _members(n, dead=()):
    return [_Member(f"10.7.0.{i}:5000", i not in dead) for i in range(n)]


async def _seeded(n_obj, n_nodes, **kw):
    """Provider with ``n_obj`` seated objects and a committed plan."""
    p = JaxObjectPlacement(node_axis_size=n_nodes, **kw)
    p.sync_members(_members(n_nodes))
    await p.assign_batch([ObjectId("T", str(i)) for i in range(n_obj)])
    await p.rebalance(delta=False)  # pay compiles, commit the PlanState
    return p


def _congestion(p, n_obj):
    """Quadratic congestion of the seating vs the integer-quota ideal."""
    m = p._node_axis
    counts = np.asarray(
        [len(p._by_node.get(i, ())) for i in range(m)], np.float64
    )
    cap_alive = np.zeros(m)
    for s in p._nodes.values():
        cap_alive[s.index] = s.capacity if (s.alive and not s.cordoned) else 0.0
    quota = integer_fair_quotas(cap_alive, n_obj).astype(np.float64)
    safe = np.maximum(cap_alive, 1e-9)
    return float(np.sum(counts**2 / safe)), float(np.sum(quota**2 / safe))


# --------------------------------------------------------- residual helpers


def test_integer_fair_quotas_sum_exactly_and_respect_zeros():
    cap = np.asarray([3.0, 1.0, 0.0, 2.0])
    for n in (0, 1, 7, 100, 12345):
        q = integer_fair_quotas(cap, n)
        assert q.sum() == n
        assert q[2] == 0  # zero capacity never gets a seat
        # Largest-remainder shares stay within 1 of the real-valued share.
        exact = cap / cap.sum() * n
        assert np.all(np.abs(q - exact) < 1.0)


def test_integer_fair_quotas_all_zero_capacity_is_empty():
    q = integer_fair_quotas(np.zeros(4), 10)
    assert q.sum() == 0  # degenerate: nothing schedulable, nothing promised


def test_residual_capacity_assign_fills_residuals_exactly():
    residual = np.asarray([2, 0, 3, 1])
    score = np.asarray([0.5, 9.9, 0.1, 0.7])
    out = residual_capacity_assign(score, residual)
    assert out.shape == (6,)
    assert np.array_equal(np.bincount(out, minlength=4), residual)
    # Better-scored nodes fill first (displaced objects are interchangeable
    # under the flat cost model, so only the per-node counts are binding —
    # but the ordering keeps the fill deterministic).
    assert out[0] == 2


# ------------------------------------------------------------ delta solves


@pytest.mark.parametrize("mode", ["sinkhorn", "scaling", "greedy"])
async def test_delta_moves_exactly_the_displaced_set(mode):
    n_obj, n_nodes = 512, 8
    p = await _seeded(n_obj, n_nodes, mode=mode)
    pre = dict(p._placements)
    dead_idx = p._nodes[_members(n_nodes)[0].address].index
    p.sync_members(_members(n_nodes, dead={0}))
    moved = await p.rebalance()
    assert p.stats.mode == f"{mode}+delta"
    assert p.stats.displaced == sum(1 for v in pre.values() if v == dead_idx)
    assert moved == p.stats.displaced
    # ZERO undisplaced moves: objects off the dead node kept their seats.
    assert all(
        p._placements[k] == v for k, v in pre.items() if v != dead_idx
    )
    # Nothing seated on the dead node; survivors at integer fair quotas.
    counts = [len(p._by_node.get(i, ())) for i in range(p._node_axis)]
    assert counts[dead_idx] == 0
    num, den = _congestion(p, n_obj)
    assert num <= 1.05 * den


async def test_delta_cost_parity_with_full_solve():
    """Same churn event, delta vs full: identical per-node seat counts
    (both land on the integer fair quotas), so cost parity is exact."""
    n_obj, n_nodes = 600, 6
    results = {}
    for delta in (True, False):
        p = await _seeded(n_obj, n_nodes, mode="sinkhorn")
        p.sync_members(_members(n_nodes, dead={1}))
        await p.rebalance(delta=delta)
        num, den = _congestion(p, n_obj)
        results[delta] = num
        assert num <= 1.05 * den
    assert results[True] <= 1.05 * results[False]


async def test_delta_threshold_routes_big_events_to_full_solve():
    # Killing 1 of 3 nodes displaces ~33% > threshold 10% -> full path.
    p = await _seeded(300, 3, mode="sinkhorn", delta_threshold=0.10)
    p.sync_members(_members(3, dead={0}))
    await p.rebalance()
    assert "+delta" not in p.stats.mode
    assert p._plan is not None and p._plan.delta_solves == 0


async def test_delta_threshold_zero_disables_deltas():
    p = await _seeded(256, 8, mode="sinkhorn", delta_threshold=0.0)
    p.sync_members(_members(8, dead={0}))
    await p.rebalance()
    assert "+delta" not in p.stats.mode


async def test_delta_true_overrides_threshold_false_forces_full():
    p = await _seeded(300, 3, mode="sinkhorn", delta_threshold=0.0)
    p.sync_members(_members(3, dead={0}))
    moved = await p.rebalance(delta=True)  # force past every gate
    assert p.stats.mode == "sinkhorn+delta"
    assert moved == p.stats.displaced > 0
    p.sync_members(_members(3, dead={0, 1}))
    await p.rebalance(delta=False)  # force the full pipeline
    assert "+delta" not in p.stats.mode


async def test_max_delta_solves_forces_periodic_full_solve():
    p = await _seeded(512, 8, mode="sinkhorn", max_delta_solves=1)
    p.sync_members(_members(8, dead={0}))
    await p.rebalance()
    assert p.stats.mode == "sinkhorn+delta"
    assert p._plan.delta_solves == 1
    p.sync_members(_members(8, dead={0, 1}))
    await p.rebalance()  # staleness bound trips -> full re-solve
    assert "+delta" not in p.stats.mode
    assert p._plan.delta_solves == 0  # full solve resets the counter


async def test_tripped_audit_marks_plan_stale_next_solve_full():
    # An impossible audit bound (<1.0) trips on any delta, marking the
    # plan stale; the NEXT churn event must go through the full pipeline.
    p = await _seeded(512, 8, mode="sinkhorn", delta_audit_ratio=0.5)
    p.sync_members(_members(8, dead={0}))
    await p.rebalance()
    assert p.stats.mode == "sinkhorn+delta"
    assert p._plan.stale
    p.sync_members(_members(8, dead={0, 1}))
    await p.rebalance()
    assert "+delta" not in p.stats.mode
    assert not p._plan.stale


async def test_epoch_discard_mid_delta_leaves_directory_untouched():
    p = await _seeded(512, 8, mode="sinkhorn")
    plan_before = p._plan
    p.sync_members(_members(8, dead={0}))
    pre = dict(p._placements)

    real_refresh = p._class_refresh

    def racing_refresh(*a, **kw):
        # Simulate churn landing while the solver thread runs: any epoch
        # bump (allocation, update, sibling solve) must discard this solve.
        p._epoch += 1
        return real_refresh(*a, **kw)

    p._class_refresh = racing_refresh
    moved = await p.rebalance()
    assert moved == 0
    assert p.stats.discarded
    assert p.stats.mode == "sinkhorn+delta"
    assert dict(p._placements) == pre  # nothing applied
    assert p._plan is plan_before  # plan not replaced by a discarded solve
    # The event is still serviceable: a clean retry lands normally.
    p._class_refresh = real_refresh
    moved = await p.rebalance()
    assert not p.stats.discarded and moved > 0


async def test_no_schedulable_capacity_outage_then_recovery():
    p = await _seeded(256, 4, mode="sinkhorn")
    pre = dict(p._placements)
    p.sync_members(_members(4, dead={0, 1, 2, 3}))
    moved = await p.rebalance()
    # Total outage: reshuffling seats among dead nodes is pure churn —
    # stay put (delta path must NOT engage on the degenerate shape).
    assert moved == 0
    assert p.stats.mode.endswith("+no_capacity")
    assert dict(p._placements) == pre
    p.sync_members(_members(4))
    moved = await p.rebalance()
    assert not p.stats.mode.endswith("+no_capacity")
    num, den = _congestion(p, 256)
    assert num <= 1.05 * den


async def test_node_return_rebalances_overflow_onto_it():
    """A RETURNING node shrinks survivor quotas; the over-quota overflow
    (and only it) re-seats onto the recovered capacity."""
    p = await _seeded(400, 4, mode="sinkhorn")
    p.sync_members(_members(4, dead={0}))
    await p.rebalance()
    pre = dict(p._placements)
    p.sync_members(_members(4))  # node 0 comes back
    moved = await p.rebalance()
    if "+delta" in p.stats.mode:
        # Overflow-only displacement: ~n/4 objects move onto the returnee.
        assert moved == p.stats.displaced
        assert moved <= 110  # ~100 expected, never a global reshuffle
    back_idx = p._nodes[_members(4)[0].address].index
    assert len(p._by_node.get(back_idx, ())) > 0
    undisplaced_kept = sum(
        1 for k, v in pre.items() if p._placements[k] == v
    )
    assert undisplaced_kept >= len(pre) - moved


# ---------------------------------------------------- warm-start parity


def _balanced_problem(key, n, m, scale=1.0):
    rng = np.random.default_rng(key)
    cost = rng.uniform(0.0, scale, size=(n, m)).astype(np.float32)
    mass = np.ones((n,), np.float32)
    cap = (np.ones((m,), np.float32) * n / m).astype(np.float32)
    return jnp.asarray(cost), jnp.asarray(mass), jnp.asarray(cap)


def test_warm_start_from_converged_is_a_fixed_point():
    cost, mass, cap = _balanced_problem(0, 96, 6)
    f0, g0, err0 = sinkhorn(cost, mass, cap, eps=0.05, n_iters=200)
    f1, g1, err1 = sinkhorn(cost, mass, cap, eps=0.05, n_iters=4, g_init=g0)
    # 4 warm iterations from the converged dual == converged.
    assert float(err1) <= float(err0) + 1e-4
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), atol=1e-3)


def test_warm_start_accelerates_after_perturbation():
    cost, mass, cap = _balanced_problem(1, 128, 8)
    _f, g_conv, _e = sinkhorn(cost, mass, cap, eps=0.05, n_iters=200)
    # Perturb capacity (one node derated) — the churn shape deltas see.
    cap2 = np.asarray(cap).copy()
    cap2[0] *= 0.5
    cap2 = jnp.asarray(cap2 / cap2.sum() * np.asarray(mass).sum())
    _f, _g, err_warm = sinkhorn(cost, mass, cap2, eps=0.05, n_iters=8, g_init=g_conv)
    _f, _g, err_cold = sinkhorn(cost, mass, cap2, eps=0.05, n_iters=8)
    assert float(err_warm) <= float(err_cold) + 1e-5


def test_scaling_warm_start_matches_log_domain_reference():
    cost, mass, cap = _balanced_problem(2, 80, 5)
    _f, g_seed, _e = sinkhorn(cost, mass, cap, eps=0.05, n_iters=30)
    fs, gs, _ = scaling_sinkhorn(cost, mass, cap, eps=0.05, n_iters=40, g_init=g_seed)
    fl, gl, _ = sinkhorn(cost, mass, cap, eps=0.05, n_iters=40, g_init=g_seed)
    # Potentials agree up to the shared constant gauge.
    shift = float(np.median(np.asarray(gs) - np.asarray(gl)))
    np.testing.assert_allclose(
        np.asarray(gs) - shift, np.asarray(gl), atol=5e-2
    )


def test_scaling_warm_start_survives_wide_cost_ranges():
    """Per-row gauge shift must survive warm starts: cost-range/eps >> 88
    underflows a global-shift scaling form to all-zero kernels (the r3
    regression) — warm-seeded or not."""
    rng = np.random.default_rng(3)
    n, m = 64, 4
    row_scale = np.exp(rng.uniform(0.0, 8.0, size=(n, 1)))
    cost = jnp.asarray((rng.uniform(0.0, 1.0, (n, m)) * row_scale).astype(np.float32))
    mass = jnp.ones((n,), jnp.float32)
    cap = jnp.ones((m,), jnp.float32) * (n / m)
    _f, g_seed, _e = sinkhorn(cost, mass, cap, eps=0.05, n_iters=30)
    fs, gs, err = scaling_sinkhorn(
        cost, mass, cap, eps=0.05, n_iters=60, g_init=g_seed
    )
    assert np.all(np.isfinite(np.asarray(fs)))
    assert np.all(np.isfinite(np.asarray(gs)))
    fl, gl, err_l = sinkhorn(cost, mass, cap, eps=0.05, n_iters=60, g_init=g_seed)
    # Marginal violation tracks the log-domain reference — no divergence.
    assert float(err) <= 2.0 * float(err_l) + 1e-3


def test_warm_start_with_nonfinite_seed_entries_cold_fills():
    # A plan solved before a node registered carries -inf for it; warm
    # starts must treat those entries as cold (0), not propagate them.
    cost, mass, cap = _balanced_problem(4, 60, 6)
    _f, g0, _e = sinkhorn(cost, mass, cap, eps=0.05, n_iters=30)
    g_hole = np.asarray(g0).copy()
    g_hole[2] = -np.inf
    for solver in (sinkhorn, scaling_sinkhorn):
        f, g, err = solver(
            cost, mass, cap, eps=0.05, n_iters=40, g_init=jnp.asarray(g_hole)
        )
        assert np.all(np.isfinite(np.asarray(g)))
        assert float(err) < 1.0


# ------------------------------------------------------------- churn soak


async def _churn_ab(n_obj, n_nodes):
    p = await _seeded(n_obj, n_nodes, mode="sinkhorn")
    # Warm both code paths' compiles before timing.
    p.sync_members(_members(n_nodes, dead={0}))
    t0 = time.perf_counter()
    await p.rebalance(delta=False)
    full_ms = (time.perf_counter() - t0) * 1e3
    p.sync_members(_members(n_nodes, dead={0, 1}))
    await p.rebalance()
    assert p.stats.mode == "sinkhorn+delta"
    dead = {0, 1, 2}
    p.sync_members(_members(n_nodes, dead=dead))
    t0 = time.perf_counter()
    moved = await p.rebalance()
    delta_ms = (time.perf_counter() - t0) * 1e3
    assert p.stats.mode == "sinkhorn+delta"
    assert moved == p.stats.displaced
    num, den = _congestion(p, n_obj)
    assert num <= 1.05 * den
    return full_ms, delta_ms


async def test_churn_delta_beats_full_small():
    """Tier-1 variant of the 1M soak: the delta event must not regress to
    full-solve cost (the hard >=10x bar is measured at 1M by
    ``bench.py --delta``, where the O(N) snapshot dominates)."""
    full_ms, delta_ms = await _churn_ab(20_000, 16)
    assert delta_ms < full_ms  # strictly cheaper even at toy scale


@pytest.mark.slow
async def test_churn_soak_1m_delta_speedup():
    """1M x 64 churn soak (the bench.py --delta acceptance shape): a
    single-node death reacts >=10x faster through the delta path, with a
    sequence of deltas staying quota-exact."""
    full_ms, delta_ms = await _churn_ab(1_048_576, 64)
    assert delta_ms * 10 <= full_ms
