"""Span correlation ids + sink behavior (reference: nested `tracing` spans,
service.rs:192-369, exported via OTLP in the observability example)."""

import asyncio

import pytest

from rio_tpu import tracing


@pytest.fixture(autouse=True)
def _clean_sinks():
    tracing.clear_sinks()
    yield
    tracing.clear_sinks()


def test_null_path_is_shared_and_silent():
    got = tracing.span("anything", key="value")
    assert got is tracing.span("other")  # one shared null object
    with got as s:
        assert s is None
    assert tracing.current_trace_id() is None


def test_parent_child_correlation():
    seen = []
    tracing.add_sink(seen.append)
    with tracing.span("parent") as p:
        assert tracing.current_trace_id() == p.trace_id
        with tracing.span("child") as c:
            assert c.trace_id == p.trace_id
            assert c.parent_id == p.span_id
        with tracing.span("sibling") as s2:
            assert s2.parent_id == p.span_id
    assert tracing.current_trace_id() is None
    assert [s.name for s in seen] == ["child", "sibling", "parent"]
    assert len({s.span_id for s in seen}) == 3
    assert len(seen[0].trace_id) == 32 and len(seen[0].span_id) == 16


def test_propagation_across_awaits_and_tasks():
    """contextvars carry the trace through awaits; tasks inherit a snapshot."""

    async def main():
        tracing.add_sink(lambda s: None)
        with tracing.span("root") as root:

            async def child_task():
                with tracing.span("in-task") as s:
                    return s.trace_id, s.parent_id

            trace_id, parent_id = await asyncio.create_task(child_task())
            assert trace_id == root.trace_id
            assert parent_id == root.span_id

    asyncio.run(main())


def test_concurrent_tasks_get_distinct_traces():
    async def main():
        tracing.add_sink(lambda s: None)

        async def one():
            with tracing.span("r") as s:
                await asyncio.sleep(0.01)
                assert tracing.current_trace_id() == s.trace_id
                return s.trace_id

        ids = await asyncio.gather(*[one() for _ in range(8)])
        assert len(set(ids)) == 8

    asyncio.run(main())


def test_sink_exception_does_not_break_request():
    def bad_sink(span):
        raise RuntimeError("boom")

    tracing.add_sink(bad_sink)
    with tracing.span("guarded"):
        pass  # must not raise


def test_otel_bridge():
    """The SDK bridge replays rio-tpu spans with ids, attrs, and timestamps.

    Runs against the real opentelemetry SDK when installed (it is in the dev
    env) via an in-memory exporter; otherwise asserts the clean ImportError.
    """
    try:
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import SimpleSpanProcessor
        from opentelemetry.sdk.trace.export.in_memory_span_exporter import (
            InMemorySpanExporter,
        )
    except ImportError:
        from rio_tpu.otel import otlp_sink

        with pytest.raises(ImportError, match="opentelemetry"):
            otlp_sink()
        return

    from rio_tpu.otel import _SdkSink

    provider = TracerProvider()
    exporter = InMemorySpanExporter()
    provider.add_span_processor(SimpleSpanProcessor(exporter))
    sink = _SdkSink(provider.get_tracer("test"))
    tracing.add_sink(sink)
    with tracing.span("outer", object="Obj.1"):
        with tracing.span("inner", n=3):
            pass
    spans = {s.name: s for s in exporter.get_finished_spans()}
    assert set(spans) == {"outer", "inner"}
    inner, outer = spans["inner"], spans["outer"]
    assert inner.attributes["rio.trace_id"] == outer.attributes["rio.trace_id"]
    assert inner.attributes["rio.parent_id"] == outer.attributes["rio.span_id"]
    assert inner.attributes["n"] == 3
    assert outer.attributes["object"] == "Obj.1"
    assert outer.end_time >= outer.start_time > 0


def test_request_path_spans_share_one_trace():
    """The service request root correlates placement/activate/dispatch."""
    from collections import defaultdict

    from rio_tpu import AppData, LocalObjectPlacement, LocalStorage, Registry
    from rio_tpu import ServiceObject, handler, message
    from rio_tpu.cluster.storage import Member
    from rio_tpu.protocol import RequestEnvelope
    from rio_tpu.service import Service
    from rio_tpu import codec

    @message(name="trace.Hit")
    class Hit:
        pass

    class Traced(ServiceObject):
        @handler
        async def hit(self, msg: Hit, ctx: AppData) -> Hit:
            return msg

    traces = defaultdict(list)
    tracing.add_sink(lambda s: traces[s.trace_id].append(s.name))

    async def main():
        members = LocalStorage()
        await members.push(Member.from_address("127.0.0.1:7001", active=True))
        svc = Service(
            address="127.0.0.1:7001",
            registry=Registry().add_type(Traced),
            object_placement=LocalObjectPlacement(),
            members_storage=members,
            app_data=AppData(),
        )
        env = RequestEnvelope("Traced", "t1", "trace.Hit", codec.serialize(Hit()))
        resp = await svc.call(env)
        assert resp.is_ok

    asyncio.run(main())
    (names,) = [v for v in traces.values() if "request" in v]
    assert set(names) >= {"request", "placement_lookup", "handler_dispatch"}
