"""Hot-key scenarios over the read-scale subsystem.

Three tiers of evidence:

- a fast deterministic mechanism check (tier-1): the zipf driver's
  replica-read mode actually serves standby reads and the staleness audit
  sees zero violations;
- the slow zipf A/B: replica reads bound the hot key's p99 to <= 0.6x the
  read-through-primary baseline under the identical seeded stream;
- the slow chaos run: the primary dies mid-read-storm and the failover
  loses zero acked writes while reads keep flowing.
"""

import asyncio

import pytest

from rio_tpu import AdminCommand, ReadScaleConfig, Registry
from rio_tpu.registry import ObjectId, type_id
from rio_tpu.replication import ReplicationConfig
from rio_tpu.utils.hotkey_live import (
    Bump,
    Profile,
    ReadProfile,
    Snap,
    _run_once,
    measure_hotkey,
    zipf_keys,
)

from .server_utils import Cluster, run_integration_test

TNAME = type_id(Profile)


def build_registry() -> Registry:
    return Registry().add_type(Profile)


def test_zipf_keys_deterministic_and_skewed():
    a = zipf_keys(32, 2000, hot_fraction=0.3, seed=11)
    b = zipf_keys(32, 2000, hot_fraction=0.3, seed=11)
    assert a == b
    hot_share = a.count(0) / len(a)
    assert 0.2 < hot_share < 0.4
    assert len(set(a)) > 10  # the tail is actually populated


def test_replica_reads_serve_standbys_with_zero_staleness_violations():
    """Fast tier-1 variant of the zipf scenario: small stream, heavy skew,
    hot arrival rate well above the primary's serialized-read ceiling, so
    the shed -> seat-hint -> standby path must engage — and the version
    audit must stay inside the staleness contract."""
    out = asyncio.run(
        _run_once(
            replica_reads=True,
            n_keys=6,
            n_requests=180,
            rate=600.0,
            hot_fraction=0.5,
            work_s=0.006,
            write_fraction=0.05,
            seed=3,
            max_inflight=8,
        )
    )
    assert out["requests"] == 180
    assert out["staleness_violations"] == 0
    assert out["standby_reads"] > 0
    assert out["client_standby_routes"] > 0
    # The hot key's reads were genuinely fanned out, not just re-queued.
    assert len(out["hot_served_by"]) >= 2


@pytest.mark.slow
def test_zipf_hot_key_p99_scaleout():
    """The acceptance A/B: same seeded zipf stream, hot-key p99 with
    replica reads <= 0.6x read-through-primary, zero staleness violations."""
    out = asyncio.run(measure_hotkey())
    assert out["replica_reads"]["standby_reads"] > 0
    assert out["replica_reads"]["staleness_violations"] == 0
    assert out["baseline"]["staleness_violations"] == 0
    assert out["hot_p99_ratio"] <= 0.6, out


async def _wait_dead(cluster: Cluster, address: str, timeout: float = 10.0) -> None:
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if not await cluster.members.is_active(address):
            return
        await asyncio.sleep(0.02)
    raise TimeoutError(f"{address} never went inactive")


@pytest.mark.slow
def test_promote_during_read_storm_loses_no_acked_writes():
    """Chaos: kill the primary under a live read storm on the hot actor.

    The storm keeps hammering `@readonly` reads (standby-served, forwarded,
    or bounced through dead-owner failover) while writes continue; after the
    epoch-fenced promotion every acked write must be visible and no read may
    ever have surfaced a version beyond what was acked."""

    async def body(cluster: Cluster):
        client = cluster.client(
            read_scale=ReadScaleConfig(max_staleness_s=2.0, max_lag_seq=4)
        )
        try:
            acked = 0
            out = await client.send(Profile, "star", Bump(amount=1), returns=Snap)
            acked += 1
            primary_addr = out.address
            held, epoch = await cluster.placement.standbys(
                ObjectId(TNAME, "star")
            )
            assert held and primary_addr not in held

            versions_seen: list[int] = []
            storm_errors = [0]
            stop = asyncio.Event()

            async def storm() -> None:
                while not stop.is_set():
                    try:
                        snap = await client.send(
                            Profile,
                            "star",
                            ReadProfile(work_s=0.001),
                            returns=Snap,
                        )
                        versions_seen.append(snap.version)
                    except Exception:
                        # Transient dial failures while the primary dies are
                        # the chaos under test; the storm itself must not die.
                        storm_errors[0] += 1
                    await asyncio.sleep(0.002)

            readers = [asyncio.create_task(storm()) for _ in range(6)]
            try:
                for _ in range(9):
                    out = await client.send(
                        Profile, "star", Bump(amount=1), returns=Snap
                    )
                    acked += 1
                await asyncio.sleep(0.15)  # storm reads the steady state

                primary = next(
                    s for s in cluster.servers if s.local_address == primary_addr
                )
                primary.admin_sender().send(AdminCommand.server_exit())
                await _wait_dead(cluster, primary_addr)

                # Writes resumed mid-storm drive the failover: a survivor's
                # dead-owner branch promotes the standby via the epoch CAS.
                for _ in range(5):
                    out = await client.send(
                        Profile, "star", Bump(amount=1), returns=Snap
                    )
                    acked += 1
                assert out.address in held
                await asyncio.sleep(0.2)  # storm reads the new primary
            finally:
                stop.set()
                await asyncio.gather(*readers, return_exceptions=True)

            final = await client.send(Profile, "star", ReadProfile(), returns=Snap)
            # THE guarantee: zero acked writes lost across the promotion.
            assert final.version == acked
            # No read ever surfaced a version beyond the acked history, and
            # the storm did observe real progress across the failover.
            assert versions_seen and max(versions_seen) <= acked
            assert min(versions_seen) >= 1
            promotions = sum(
                s.replication_manager.stats.promotions
                for s in cluster.servers
                if s.replication_manager is not None
            )
            assert promotions == 1
            _, epoch2 = await cluster.placement.standbys(ObjectId(TNAME, "star"))
            assert epoch2 == epoch + 1
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=3,
            server_kwargs={
                "replication_config": ReplicationConfig(
                    k=1, anti_entropy_interval=0.2, seat_ttl=0.3
                ),
                "read_scale_config": ReadScaleConfig(
                    max_staleness_s=2.0, max_lag_seq=4
                ),
            },
        )
    )
