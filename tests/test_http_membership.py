"""HTTP membership end-to-end: credential-less cluster join.

Reference: ``rio-rs/src/cluster/storage/http.rs:35-150`` — a server exposes
the read-only members API (wired via ``http_members_address``,
``server.rs:205-229``) and a client joins the cluster through
``HttpMembershipStorage`` with no database credentials; every write op on
that storage fails with the read-only error.
"""

import asyncio
import socket

import pytest

from rio_tpu import (
    Client,
    LocalObjectPlacement,
    LocalStorage,
    Registry,
    Server,
)
from rio_tpu.cluster.membership_protocol import LocalClusterProvider
from rio_tpu.cluster.storage import Member
from rio_tpu.cluster.storage.http import HttpMembershipStorage
from rio_tpu.errors import MembershipReadOnly
from rio_tpu.utils.routing_live import Echo, EchoActor


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.asyncio
async def test_http_membership_end_to_end():
    members = LocalStorage()
    http_port = _free_port()
    server = Server(
        address="127.0.0.1:0",
        registry=Registry().add_type(EchoActor),
        cluster_provider=LocalClusterProvider(members),
        object_placement_provider=LocalObjectPlacement(),
        http_members_address=f"127.0.0.1:{http_port}",
    )
    await server.prepare()
    await server.bind()
    task = asyncio.create_task(server.run())
    try:
        http_members = HttpMembershipStorage(f"127.0.0.1:{http_port}")
        # Wait until the API is up AND the node registered itself active.
        deadline = asyncio.get_event_loop().time() + 10.0
        while asyncio.get_event_loop().time() < deadline:
            try:
                if await http_members.active_members():
                    break
            except Exception:
                pass
            await asyncio.sleep(0.05)
        listed = await http_members.members()
        assert [m.address for m in listed] == [server.local_address]

        # A client built purely on the HTTP view completes a round trip.
        client = Client(http_members)
        out = await client.send(EchoActor, "h1", Echo(value=41), returns=Echo)
        assert out.value == 41
        client.close()

        # Single-member endpoint (GET /members/{ip}/{port}).
        ip, _, port = server.local_address.rpartition(":")
        one = await http_members._get(f"/members/{ip}/{port}")
        assert one is not None and one["ip"] == ip and one["port"] == int(port)
        assert await http_members._get("/members/10.9.9.9/1") is None  # 404

        # Write surface is read-only by design (reference http.rs:85-150).
        with pytest.raises(MembershipReadOnly):
            await http_members.push(Member.from_address("10.0.0.9:1"))
        with pytest.raises(MembershipReadOnly):
            await http_members.remove("10.0.0.9", 1)
        with pytest.raises(MembershipReadOnly):
            await http_members.set_is_active("10.0.0.9", 1, True)
        with pytest.raises(MembershipReadOnly):
            await http_members.notify_failure("10.0.0.9", 1)
    finally:
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
