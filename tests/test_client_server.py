"""Client↔server integration tests.

Models the reference's ``rio-rs/tests/client_server_integration_test.rs``
(request/response, typed app-error round trip, redirect across a 10-server
cluster) plus ``server_internal_client_test.rs`` (actor→actor send).
"""

import asyncio

import pytest

from rio_tpu import AppData, Registry, ServiceObject, handler, message, wire_error
from rio_tpu.errors import RetryExhausted

from .server_utils import Cluster, run_integration_test


@message
class Ask:
    text: str = ""


@message
class Answer:
    text: str = ""
    times: int = 0


@message
class Fanout:
    target_id: str = ""
    text: str = ""


@wire_error
class Unanswerable(Exception):
    pass


class Oracle(ServiceObject):
    def __init__(self):
        self.times = 0

    @handler
    async def ask(self, msg: Ask, ctx: AppData) -> Answer:
        if msg.text == "unanswerable":
            raise Unanswerable(msg.text, 42)
        self.times += 1
        return Answer(text=f"echo:{msg.text}", times=self.times)

    @handler
    async def fanout(self, msg: Fanout, ctx: AppData) -> Answer:
        # actor→actor proxying through the internal client
        return await ServiceObject.send(
            ctx, Oracle, msg.target_id, Ask(text=msg.text), returns=Answer
        )


def build_registry() -> Registry:
    r = Registry()
    r.add_type(Oracle)
    return r


def test_request_response():
    async def body(cluster: Cluster):
        client = cluster.client()
        out = await client.send(Oracle, "oracle-1", Ask(text="hi"), returns=Answer)
        assert out == Answer(text="echo:hi", times=1)
        out = await client.send(Oracle, "oracle-1", Ask(text="again"), returns=Answer)
        assert out.times == 2  # same live instance served both calls
        assert await cluster.is_allocated("Oracle", "oracle-1")
        client.close()

    asyncio.run(run_integration_test(body, registry_builder=build_registry, num_servers=2))


def test_typed_app_error_roundtrip():
    async def body(cluster: Cluster):
        client = cluster.client()
        with pytest.raises(Unanswerable) as ei:
            await client.send(Oracle, "o", Ask(text="unanswerable"), returns=Answer)
        assert ei.value.args == ("unanswerable", 42)
        # the object survives a typed error (no deallocation)
        out = await client.send(Oracle, "o", Ask(text="ok"), returns=Answer)
        assert out.times == 1
        client.close()

    asyncio.run(run_integration_test(body, registry_builder=build_registry, num_servers=2))


def test_redirect_across_ten_servers():
    async def body(cluster: Cluster):
        # Allocate 20 objects via one client; each self-assigns somewhere.
        c1 = cluster.client()
        for i in range(20):
            await c1.send(Oracle, f"o{i}", Ask(text="seed"), returns=Answer)
        # A fresh client has a cold placement cache: its random picks will
        # mostly be wrong and must be redirected to the true owners.
        c2 = cluster.client()
        for i in range(20):
            out = await c2.send(Oracle, f"o{i}", Ask(text="x"), returns=Answer)
            assert out.times == 2, f"o{i} must hit the same instance (got {out})"
        # Placement cache now warm: repeated sends are direct.
        for i in range(20):
            out = await c2.send(Oracle, f"o{i}", Ask(text="y"), returns=Answer)
            assert out.times == 3
        c1.close()
        c2.close()

    asyncio.run(
        run_integration_test(body, registry_builder=build_registry, num_servers=10)
    )


def test_internal_client_actor_to_actor():
    async def body(cluster: Cluster):
        client = cluster.client()
        out = await client.send(
            Oracle, "proxy", Fanout(target_id="proxy-target", text="via"), returns=Answer
        )
        assert out == Answer(text="echo:via", times=1)
        assert await cluster.is_allocated("Oracle", "proxy-target")
        client.close()

    # Single server: internal sends always resolve locally (the reference's
    # internal client does not follow cross-node redirects either).
    asyncio.run(run_integration_test(body, registry_builder=build_registry, num_servers=1))


def test_unknown_type_not_supported():
    async def body(cluster: Cluster):
        client = cluster.client()
        with pytest.raises(Exception) as ei:
            await client.send("GhostType", "g", Ask(), returns=Answer)
        assert "NOT_SUPPORTED" in str(ei.value)
        client.close()

    asyncio.run(run_integration_test(body, registry_builder=build_registry, num_servers=2))


def test_no_active_servers_retry_exhausts():
    async def body(cluster: Cluster):
        # Point a client at an empty membership view.
        from rio_tpu import LocalStorage
        from rio_tpu.utils import ExponentialBackoff

        client = cluster.client()
        client.members_storage = LocalStorage()
        client._active_servers = []
        client._backoff = ExponentialBackoff(initial=1e-4, cap=1e-3, max_retries=3)
        with pytest.raises(RetryExhausted):
            await client.send(Oracle, "x", Ask(), returns=Answer)

    asyncio.run(run_integration_test(body, registry_builder=build_registry, num_servers=1))


def test_client_builder_promotes_tunables():
    """Every hard-coded client tunable is reachable through the builder:
    placement LRU size, retry/backoff policy, membership view TTL."""
    from rio_tpu import ClientBuilder, LocalStorage
    from rio_tpu.utils import ExponentialBackoff

    policy = ExponentialBackoff(initial=0.5, cap=2.0, max_retries=7)
    client = (
        ClientBuilder()
        .members_storage(LocalStorage())
        .placement_cache_size(17)
        .backoff(policy)
        .membership_view_ttl(4.5)
        .build()
    )
    assert client._placement.capacity == 17
    assert client._backoff is policy
    assert client._view_ttl == 4.5
    client.close()

    # Defaults still hold when nothing is overridden.
    default = ClientBuilder().members_storage(LocalStorage()).build()
    from rio_tpu.client import DEFAULT_PLACEMENT_LRU

    assert default._placement.capacity == DEFAULT_PLACEMENT_LRU
    assert default._view_ttl == 1.0
    default.close()
