"""Durable streams + sagas: storage contract, delivery, redelivery, sagas.

The tentpole subsystem end to end: the :class:`StreamStorage` backend
contract across all four backends (fakes carry postgres/redis), the
publish → cursor → consumer delivery path on a live cluster, at-least-once
redelivery driven by the reminder subsystem after a consumer rejection,
and saga step/compensation chains with participant-side exactly-once
dedup.
"""

import asyncio
from collections import defaultdict

import pytest

from rio_tpu import (
    AppData,
    LocalReminderStorage,
    Registry,
    ReminderDaemonConfig,
    ReminderStorage,
    ServiceObject,
    handler,
    message,
)
from rio_tpu.registry import wire_error
from rio_tpu.state import LocalState, StateProvider
from rio_tpu.streams import (
    LocalStreamStorage,
    StreamDelivery,
    StreamRecord,
    StreamStorage,
    Subscription,
)
from rio_tpu.streams.cursor import (
    CURSOR_TYPE,
    cursor_id,
    publish,
    subscribe_group,
    unsubscribe_group,
)
from rio_tpu.streams.saga import (
    SAGA_TYPE,
    SagaStatus,
    SagaStatusReply,
    StartSaga,
    step,
)
from rio_tpu.utils import ExponentialBackoff

from .server_utils import Cluster, run_integration_test

# ---------------------------------------------------------------------------
# storage contract (all four backends)
# ---------------------------------------------------------------------------


async def check_stream_storage(s: StreamStorage) -> None:
    """The backend contract every StreamStorage must satisfy."""
    await s.prepare()
    p = s.partition_of("orders", "k1")
    offs = [
        await s.append(StreamRecord("orders", p, 0, "M", b"x%d" % i, "k1", 1.0))
        for i in range(5)
    ]
    assert offs == [0, 1, 2, 3, 4]  # dense, 0-based
    assert await s.latest("orders", p) == 5
    # Distinct (stream, partition) logs never interleave.
    other = (p + 1) % s.num_partitions
    assert await s.append(StreamRecord("orders", other, 0, "M", b"o", "", 1.0)) == 0
    recs = await s.read("orders", p, 2, 10)
    assert [r.offset for r in recs] == [2, 3, 4]
    assert recs[0].payload == b"x2" and recs[0].message_type == "M"
    assert recs[0].key == "k1"
    assert await s.read("orders", p, 2, 2) and len(await s.read("orders", p, 2, 2)) == 2
    assert await s.read("orders", p, 99) == []
    # Subscriptions: upsert + ordered listing + removal.
    await s.subscribe(Subscription("orders", "g1", "T", 0.5))
    await s.subscribe(Subscription("orders", "g0", "T"))
    await s.subscribe(Subscription("orders", "g1", "T2", 0.25))  # overwrite
    subs = await s.subscriptions("orders")
    assert [(x.group, x.target_type) for x in subs] == [("g0", "T"), ("g1", "T2")]
    assert subs[1].redelivery_period == 0.25
    # Cursors: default 0, monotone commit, per-partition map.
    assert await s.committed("orders", "g1", p) == 0
    await s.commit("orders", "g1", p, 3)
    await s.commit("orders", "g1", p, 2)  # stale — must not regress
    assert await s.committed("orders", "g1", p) == 3
    assert await s.cursors("orders", "g1") == {p: 3}
    await s.unsubscribe("orders", "g0")
    assert [x.group for x in await s.subscriptions("orders")] == ["g1"]


@pytest.mark.asyncio
async def test_local_stream_storage():
    await check_stream_storage(LocalStreamStorage())


@pytest.mark.asyncio
async def test_sqlite_stream_storage(tmp_path):
    from rio_tpu.streams.sqlite import SqliteStreamStorage

    await check_stream_storage(SqliteStreamStorage(str(tmp_path / "s.db")))


@pytest.mark.asyncio
async def test_postgres_stream_storage():
    import os

    from rio_tpu.streams.postgres import PostgresStreamStorage
    from rio_tpu.utils.pg import driver_available

    dsn = os.environ.get("RIO_TPU_PG_DSN", "")
    if not driver_available() or not dsn:
        from tests import fake_pg

        fake_pg.install()
        fake_pg.reset()
        dsn = "postgresql://fake-pg/streams"
    await check_stream_storage(PostgresStreamStorage(dsn))


@pytest.mark.asyncio
async def test_redis_stream_storage():
    from rio_tpu.streams.redis import RedisStreamStorage

    from tests.fake_redis import FakeRedisServer

    srv = FakeRedisServer()
    await srv.start()
    try:
        await check_stream_storage(
            RedisStreamStorage(f"redis://127.0.0.1:{srv.port}")
        )
    finally:
        await srv.stop()


# ---------------------------------------------------------------------------
# delivery integration (live cluster)
# ---------------------------------------------------------------------------

# Global records: (group, stream, offset, payload, attempt) per sink id —
# survives re-activation and server moves (one process).
SEEN: dict[str, list[tuple]] = defaultdict(list)
REJECT: dict[str, int] = {}  # sink id -> number of deliveries to reject


@message
class Item:
    n: int = 0


@wire_error
class SinkRejected(Exception):
    pass


class Sink(ServiceObject):
    async def receive_stream(self, delivery: StreamDelivery, ctx) -> None:
        if REJECT.get(self.id, 0) > 0:
            REJECT[self.id] -= 1
            raise SinkRejected(self.id)
        item = delivery.decode(Item)
        SEEN[self.id].append(
            (delivery.group, delivery.stream, delivery.offset, item.n, delivery.attempt)
        )


def build_registry() -> Registry:
    return Registry().add_type(Sink).add_type(Account).add_type(Vetoer)


def streams_kwargs(
    storage: StreamStorage,
    reminders: LocalReminderStorage | None = None,
    state: LocalState | None = None,
    daemon: bool = False,
) -> dict:
    shared_state = state or LocalState()

    def app_data() -> AppData:
        ad = AppData().set(storage, as_type=StreamStorage)
        ad.set(shared_state, as_type=StateProvider)
        if reminders is not None:
            ad.set(reminders, as_type=ReminderStorage)
        return ad

    kwargs: dict = {"app_data_builder": app_data}
    if daemon:
        kwargs["server_kwargs"] = {
            "reminder_daemon": True,
            "reminder_daemon_config": ReminderDaemonConfig(
                poll_interval=0.05,
                lease_ttl=2.0,
                delivery_backoff=ExponentialBackoff(
                    initial=1e-3, cap=0.05, max_retries=4
                ),
            ),
        }
    return kwargs


async def wait_until(pred, timeout: float, interval: float = 0.02):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        v = pred()
        if v:
            return v
        await asyncio.sleep(interval)
    raise AssertionError(f"condition never became true within {timeout}s")


def test_publish_delivers_to_every_group():
    """Two consumer groups each see every record, in per-partition order;
    cursors advance to the log head."""
    SEEN.clear()
    REJECT.clear()
    storage = LocalStreamStorage()

    async def body(cluster: Cluster):
        ctx = cluster.servers[0].app_data
        await subscribe_group(ctx, "orders", "audit", Sink)
        await subscribe_group(ctx, "orders", "billing", Sink)
        acks = []
        for i in range(10):
            acks.append(await publish(ctx, "orders", Item(n=i), key=f"k{i % 3}"))
        assert all(isinstance(o, int) for _, o in acks)

        def total():
            rows = [r for rows in SEEN.values() for r in rows]
            groups = {r[0] for r in rows}
            return len(rows) == 20 and groups == {"audit", "billing"}

        await wait_until(total, 10.0)
        # Per (group, key-partition) delivery is in offset order.
        for sink_id, rows in SEEN.items():
            for g in ("audit", "billing"):
                offs = [r[2] for r in rows if r[0] == g]
                assert offs == sorted(offs), (sink_id, rows)
        # Cursors committed to the head of each partition.
        for group in ("audit", "billing"):
            cursors = await storage.cursors("orders", group)
            for p, committed in cursors.items():
                assert committed == await storage.latest("orders", p)

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=1,
            **streams_kwargs(storage),
        )
    )


def test_rejected_delivery_stalls_then_redelivers():
    """A consumer rejection stalls the partition (no skip, no commit); the
    reminder backstop redelivers until it lands — with attempt > 1 and no
    record lost or reordered."""
    SEEN.clear()
    REJECT.clear()
    storage = LocalStreamStorage()
    reminders = LocalReminderStorage()

    async def body(cluster: Cluster):
        ctx = cluster.servers[0].app_data
        await subscribe_group(
            ctx, "jobs", "work", Sink, redelivery_period=0.2
        )
        # All records share one key → one partition → strict order.
        REJECT["kA"] = 2  # first two delivery attempts bounce
        for i in range(4):
            await publish(ctx, "jobs", Item(n=i), key="kA")

        def done():
            rows = SEEN.get("kA", [])
            return len(rows) == 4

        await wait_until(done, 15.0)
        rows = SEEN["kA"]
        assert [r[3] for r in rows] == [0, 1, 2, 3]  # nothing lost/reordered
        assert rows[0][4] > 1  # offset 0 landed via redelivery
        p = storage.partition_of("jobs", "kA")
        deadline = asyncio.get_event_loop().time() + 5.0
        while await storage.committed("jobs", "work", p) < 4:
            assert asyncio.get_event_loop().time() < deadline, "commit never caught up"
            await asyncio.sleep(0.02)
        await unsubscribe_group(ctx, "jobs", "work")
        assert await reminders.list_object(
            CURSOR_TYPE, cursor_id("jobs", "work", p)
        ) == []

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=1,
            timeout=40.0,
            **streams_kwargs(storage, reminders=reminders, daemon=True),
        )
    )


# ---------------------------------------------------------------------------
# sagas
# ---------------------------------------------------------------------------

LEDGER: dict[str, list[str]] = defaultdict(list)  # account id -> effects


@message
class Reserve:
    amount: int = 0


@message
class Unreserve:
    amount: int = 0


@wire_error
class Vetoed(Exception):
    pass


class Account(ServiceObject):
    @handler
    async def reserve(self, msg: Reserve, ctx) -> int:
        LEDGER[self.id].append(f"reserve:{msg.amount}")
        return msg.amount

    @handler
    async def unreserve(self, msg: Unreserve, ctx) -> int:
        LEDGER[self.id].append(f"unreserve:{msg.amount}")
        return msg.amount


class Vetoer(ServiceObject):
    """Participant that rejects every action (typed error)."""

    @handler
    async def reserve(self, msg: Reserve, ctx) -> int:
        LEDGER[self.id].append("veto")
        raise Vetoed(self.id)


def test_saga_completes_across_participants():
    LEDGER.clear()

    async def body(cluster: Cluster):
        client = cluster.client()
        reply = await client.send(
            SAGA_TYPE,
            "order-1",
            StartSaga(
                steps=[
                    step(Account, "a", Reserve(amount=5), Unreserve(amount=5)),
                    step(Account, "b", Reserve(amount=7), Unreserve(amount=7)),
                ]
            ),
            returns=SagaStatusReply,
        )
        assert reply.status == "completed", reply
        assert LEDGER["a"] == ["reserve:5"]
        assert LEDGER["b"] == ["reserve:7"]
        # Idempotent restart: same saga id reports, never re-runs.
        again = await client.send(
            SAGA_TYPE, "order-1", StartSaga(steps=[]), returns=SagaStatusReply
        )
        assert again.status == "completed" and again.total == 2
        assert LEDGER["a"] == ["reserve:5"]
        status = await client.send(
            SAGA_TYPE, "order-1", SagaStatus(), returns=SagaStatusReply
        )
        assert status.status == "completed"
        client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=1,
            **streams_kwargs(LocalStreamStorage()),
        )
    )


def test_saga_compensates_in_reverse_on_rejection():
    LEDGER.clear()

    async def body(cluster: Cluster):
        client = cluster.client()
        reply = await client.send(
            SAGA_TYPE,
            "order-2",
            StartSaga(
                steps=[
                    step(Account, "a", Reserve(amount=5), Unreserve(amount=5)),
                    step(Account, "b", Reserve(amount=7), Unreserve(amount=7)),
                    step(Vetoer, "v", Reserve(amount=9), Unreserve(amount=9)),
                ]
            ),
            returns=SagaStatusReply,
        )
        assert reply.status == "compensated", reply
        assert "Vetoed" in reply.error
        # Completed steps undone, in reverse order; the rejected step has
        # no compensation effect (it never completed).
        assert LEDGER["a"] == ["reserve:5", "unreserve:5"]
        assert LEDGER["b"] == ["reserve:7", "unreserve:7"]
        assert LEDGER["v"] == ["veto"]
        client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=1,
            **streams_kwargs(LocalStreamStorage()),
        )
    )


def test_saga_step_dedup_is_exactly_once():
    """A re-sent step (coordinator resume after a crash mid-send) is
    absorbed by the participant's persisted ledger."""
    LEDGER.clear()

    async def body(cluster: Cluster):
        from rio_tpu.streams import SagaStep
        from rio_tpu import codec

        client = cluster.client()
        saga_step = SagaStep(
            saga_id="s-dup",
            step=0,
            kind="action",
            message_type="Reserve",
            payload=codec.serialize(Reserve(amount=3)),
        )
        await client.send("Account", "dup", saga_step)
        await client.send("Account", "dup", saga_step)  # duplicate
        assert LEDGER["dup"] == ["reserve:3"]
        # Same step, different kind → a distinct effect (compensation).
        comp = SagaStep(
            saga_id="s-dup",
            step=0,
            kind="compensate",
            message_type="Unreserve",
            payload=codec.serialize(Unreserve(amount=3)),
        )
        await client.send("Account", "dup", comp)
        await client.send("Account", "dup", comp)
        assert LEDGER["dup"] == ["reserve:3", "unreserve:3"]
        client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=1,
            **streams_kwargs(LocalStreamStorage()),
        )
    )
