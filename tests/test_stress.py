"""Scale/concurrency stress tests.

Mirrors the reference's concurrency regression test: 1M actors dispatching
through one shared registry, with re-entrant proxy sends, proving dispatch
never holds a map-wide lock across an ``await``
(``rio-rs/src/registry/mod.rs:561-625`` ``test_proxy_deadlock``). Plus the
server-path variant through the internal-client queue
(``rio-rs/src/server.rs:309-332``), and a node-churn re-solve exercise for
the placement provider (BASELINE.md row 4)."""

import asyncio
import os

import pytest

from rio_tpu import AppData, Registry, ServiceObject, handler, message

# Reference parity: test_proxy_deadlock runs 1M actors unconditionally
# (rio-rs/src/registry/mod.rs:561-563). RIO_TPU_STRESS_FAST=1 drops to 200k
# for quick local iteration.
N_ACTORS = 200_000 if os.environ.get("RIO_TPU_STRESS_FAST") else 1_000_000
N_CONCURRENT = 5_000


@message
class SHop:
    target: str = ""


@message
class SDone:
    hops: int = 0


class StressProxy(ServiceObject):
    @handler
    async def hop(self, msg: SHop, ctx: AppData) -> SDone:
        if msg.target:
            # Re-entrant dispatch through the SAME registry while this
            # object's lock is held — deadlocks if dispatch serializes on a
            # map-wide lock across await.
            reg: Registry = ctx.get(Registry)
            out = await reg.send(
                "StressProxy", msg.target, SHop(), ctx, returns=SDone
            )
            return SDone(hops=out.hops + 1)
        return SDone(hops=1)


def test_million_actor_proxy_dispatch_no_deadlock():
    reg = Registry()
    reg.add_type(StressProxy)
    for i in range(N_ACTORS):
        reg.insert("StressProxy", str(i), reg.new_from_type("StressProxy", str(i)))
    assert reg.count_objects() == N_ACTORS

    app = AppData()
    app.set(reg)

    async def run():
        # Every task proxies through actor i to a distinct hub actor; with a
        # map-wide lock this collapses to a deadlock (outer dispatch holds
        # the map while the inner needs it) or full serialization.
        hub_base = N_ACTORS // 2
        outs = await asyncio.gather(*[
            reg.send(
                "StressProxy",
                str(i),
                SHop(target=str(hub_base + (i % 1000))),
                app,
                returns=SDone,
            )
            for i in range(N_CONCURRENT)
        ])
        assert all(o.hops == 2 for o in outs)

    asyncio.run(asyncio.wait_for(run(), 120))


def test_proxy_to_single_hub_is_serialized_not_deadlocked():
    """All proxies target ONE hub: contention on the hub's per-object lock
    must serialize cleanly, never deadlock."""
    reg = Registry()
    reg.add_type(StressProxy)
    for i in range(1001):
        reg.insert("StressProxy", str(i), reg.new_from_type("StressProxy", str(i)))
    app = AppData()
    app.set(reg)

    async def run():
        outs = await asyncio.gather(*[
            reg.send("StressProxy", str(i), SHop(target="1000"), app, returns=SDone)
            for i in range(1000)
        ])
        assert all(o.hops == 2 for o in outs)

    asyncio.run(asyncio.wait_for(run(), 60))


@message
class FanHop:
    next_id: str = ""


@message
class FanDone:
    ok: bool = True


class FanProxy(ServiceObject):
    @handler
    async def hop(self, msg: FanHop, ctx: AppData) -> FanDone:
        if msg.next_id:
            # Actor→actor through the server's internal-client queue; the
            # consumer MUST spawn dispatches (server.rs:309-332) or this
            # nests a queue wait under a held lock.
            return await ServiceObject.send(
                ctx, FanProxy, msg.next_id, FanHop(), returns=FanDone
            )
        return FanDone()


def test_internal_client_fanout_no_queue_deadlock():
    from rio_tpu import LocalObjectPlacement, LocalStorage, Server
    from rio_tpu.cluster.membership_protocol import LocalClusterProvider

    def reg():
        r = Registry()
        r.add_type(FanProxy)
        return r

    async def run():
        members = LocalStorage()
        server = Server(
            address="127.0.0.1:0",
            registry=reg(),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=LocalObjectPlacement(),
        )
        await server.prepare()
        await server.bind()
        task = asyncio.create_task(server.run())
        while not await members.active_members():
            await asyncio.sleep(0.02)
        from rio_tpu import Client

        client = Client(members)
        outs = await asyncio.gather(*[
            client.send(FanProxy, f"p{i}", FanHop(next_id=f"q{i % 50}"), returns=FanDone)
            for i in range(500)
        ])
        assert all(o.ok for o in outs)
        client.close()
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)

    asyncio.run(asyncio.wait_for(run(), 120))


# ---------------------------------------------------------------------------
# Churn re-solve (BASELINE.md row 4: 10% node churn with warm restarts)
# ---------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_churn_resolve_moves_only_affected_objects():
    from rio_tpu.object_placement import ObjectId
    from rio_tpu.object_placement.jax_placement import JaxObjectPlacement

    placement = JaxObjectPlacement(mode="sinkhorn")
    n_nodes, n_objects = 20, 2000
    for i in range(n_nodes):
        placement.register_node(f"10.0.0.{i}:50")
    ids = [ObjectId("Churn", str(i)) for i in range(n_objects)]
    await placement.assign_batch(ids)
    await placement.rebalance()
    before = {str(i): await placement.lookup(i) for i in ids}

    # 10% of nodes die; their objects must move, the rest should mostly stay.
    dead = {f"10.0.0.{i}:50" for i in range(2)}
    for addr in dead:
        await placement.clean_server(addr)
    # clean_server already unassigned the dead nodes' objects; re-place them
    # against cached potentials (the warm-start incremental path).
    orphans = [i for i in ids if await placement.lookup(i) is None]
    assert 0 < len(orphans) <= n_objects
    await placement.assign_batch(orphans)

    moved = stayed = 0
    for i in ids:
        addr = await placement.lookup(i)
        assert addr is not None and addr not in dead
        if addr == before[str(i)]:
            stayed += 1
        else:
            moved += 1
    # Only the orphans (~10%) should have moved.
    assert moved <= len(orphans)
    assert stayed >= n_objects - len(orphans)

    # A full warm re-solve after churn converges and is capacity-sane.
    moved2 = await placement.rebalance()
    counts: dict[str, int] = {}
    for i in ids:
        addr = await placement.lookup(i)
        counts[addr] = counts.get(addr, 0) + 1
    live = [a for a in counts if a not in dead]
    fair = n_objects / (n_nodes - len(dead))
    assert max(counts[a] for a in live) < 2.0 * fair
    assert moved2 <= n_objects
