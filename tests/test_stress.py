"""Scale/concurrency stress tests.

Mirrors the reference's concurrency regression test: 1M actors dispatching
through one shared registry, with re-entrant proxy sends, proving dispatch
never holds a map-wide lock across an ``await``
(``rio-rs/src/registry/mod.rs:561-625`` ``test_proxy_deadlock``). Plus the
server-path variant through the internal-client queue
(``rio-rs/src/server.rs:309-332``), and a node-churn re-solve exercise for
the placement provider (BASELINE.md row 4)."""

import asyncio
import os

import pytest

from rio_tpu import AppData, Registry, ServiceObject, handler, message

# Reference parity: test_proxy_deadlock runs 1M actors unconditionally
# (rio-rs/src/registry/mod.rs:561-563). RIO_TPU_STRESS_FAST=1 drops to 200k
# for quick local iteration.
N_ACTORS = 200_000 if os.environ.get("RIO_TPU_STRESS_FAST") else 1_000_000
N_CONCURRENT = 5_000


@message
class SHop:
    target: str = ""


@message
class SDone:
    hops: int = 0


class StressProxy(ServiceObject):
    @handler
    async def hop(self, msg: SHop, ctx: AppData) -> SDone:
        if msg.target:
            # Re-entrant dispatch through the SAME registry while this
            # object's lock is held — deadlocks if dispatch serializes on a
            # map-wide lock across await.
            reg: Registry = ctx.get(Registry)
            out = await reg.send(
                "StressProxy", msg.target, SHop(), ctx, returns=SDone
            )
            return SDone(hops=out.hops + 1)
        return SDone(hops=1)


@pytest.mark.slow
def test_million_actor_proxy_dispatch_no_deadlock():
    reg = Registry()
    reg.add_type(StressProxy)
    for i in range(N_ACTORS):
        reg.insert("StressProxy", str(i), reg.new_from_type("StressProxy", str(i)))
    assert reg.count_objects() == N_ACTORS

    app = AppData()
    app.set(reg)

    async def run():
        # Every task proxies through actor i to a distinct hub actor; with a
        # map-wide lock this collapses to a deadlock (outer dispatch holds
        # the map while the inner needs it) or full serialization.
        hub_base = N_ACTORS // 2
        outs = await asyncio.gather(*[
            reg.send(
                "StressProxy",
                str(i),
                SHop(target=str(hub_base + (i % 1000))),
                app,
                returns=SDone,
            )
            for i in range(N_CONCURRENT)
        ])
        assert all(o.hops == 2 for o in outs)

    asyncio.run(asyncio.wait_for(run(), 120))


def test_proxy_to_single_hub_is_serialized_not_deadlocked():
    """All proxies target ONE hub: contention on the hub's per-object lock
    must serialize cleanly, never deadlock."""
    reg = Registry()
    reg.add_type(StressProxy)
    for i in range(1001):
        reg.insert("StressProxy", str(i), reg.new_from_type("StressProxy", str(i)))
    app = AppData()
    app.set(reg)

    async def run():
        outs = await asyncio.gather(*[
            reg.send("StressProxy", str(i), SHop(target="1000"), app, returns=SDone)
            for i in range(1000)
        ])
        assert all(o.hops == 2 for o in outs)

    asyncio.run(asyncio.wait_for(run(), 60))


@message
class FanHop:
    next_id: str = ""


@message
class FanDone:
    ok: bool = True


class FanProxy(ServiceObject):
    @handler
    async def hop(self, msg: FanHop, ctx: AppData) -> FanDone:
        if msg.next_id:
            # Actor→actor through the server's internal-client queue; the
            # consumer MUST spawn dispatches (server.rs:309-332) or this
            # nests a queue wait under a held lock.
            return await ServiceObject.send(
                ctx, FanProxy, msg.next_id, FanHop(), returns=FanDone
            )
        return FanDone()


def test_internal_client_fanout_no_queue_deadlock():
    from rio_tpu import LocalObjectPlacement, LocalStorage, Server
    from rio_tpu.cluster.membership_protocol import LocalClusterProvider

    def reg():
        r = Registry()
        r.add_type(FanProxy)
        return r

    async def run():
        members = LocalStorage()
        server = Server(
            address="127.0.0.1:0",
            registry=reg(),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=LocalObjectPlacement(),
        )
        await server.prepare()
        await server.bind()
        task = asyncio.create_task(server.run())
        while not await members.active_members():
            await asyncio.sleep(0.02)
        from rio_tpu import Client

        client = Client(members)
        outs = await asyncio.gather(*[
            client.send(FanProxy, f"p{i}", FanHop(next_id=f"q{i % 50}"), returns=FanDone)
            for i in range(500)
        ])
        assert all(o.ok for o in outs)
        client.close()
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)

    asyncio.run(asyncio.wait_for(run(), 120))


# ---------------------------------------------------------------------------
# Churn re-solve (BASELINE.md row 4: 10% node churn with warm restarts)
# ---------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_churn_resolve_moves_only_affected_objects():
    from rio_tpu.object_placement import ObjectId
    from rio_tpu.object_placement.jax_placement import JaxObjectPlacement

    placement = JaxObjectPlacement(mode="sinkhorn")
    n_nodes, n_objects = 20, 2000
    for i in range(n_nodes):
        placement.register_node(f"10.0.0.{i}:50")
    ids = [ObjectId("Churn", str(i)) for i in range(n_objects)]
    await placement.assign_batch(ids)
    await placement.rebalance()
    before = {str(i): await placement.lookup(i) for i in ids}

    # 10% of nodes die; their objects must move, the rest should mostly stay.
    dead = {f"10.0.0.{i}:50" for i in range(2)}
    for addr in dead:
        await placement.clean_server(addr)
    # clean_server already unassigned the dead nodes' objects; re-place them
    # against cached potentials (the warm-start incremental path).
    orphans = [i for i in ids if await placement.lookup(i) is None]
    assert 0 < len(orphans) <= n_objects
    await placement.assign_batch(orphans)

    moved = stayed = 0
    for i in ids:
        addr = await placement.lookup(i)
        assert addr is not None and addr not in dead
        if addr == before[str(i)]:
            stayed += 1
        else:
            moved += 1
    # Only the orphans (~10%) should have moved.
    assert moved <= len(orphans)
    assert stayed >= n_objects - len(orphans)

    # A full warm re-solve after churn converges and is capacity-sane.
    moved2 = await placement.rebalance()
    counts: dict[str, int] = {}
    for i in ids:
        addr = await placement.lookup(i)
        counts[addr] = counts.get(addr, 0) + 1
    live = [a for a in counts if a not in dead]
    fair = n_objects / (n_nodes - len(dead))
    assert max(counts[a] for a in live) < 2.0 * fair
    assert moved2 <= n_objects


@pytest.mark.skipif(
    not os.environ.get("RIO_TPU_STRESS_10M"),
    reason="row-5-scale host-directory stress: set RIO_TPU_STRESS_10M=1 "
    "(~2 GB RSS, minutes; last banked run in the docstring below)",
)
@pytest.mark.asyncio
@pytest.mark.slow
async def test_row5_scale_directory_host_side():
    """BASELINE row-5's HOST half: the directory at 10M objects x 1k nodes.

    The device solve at this scale is covered by the hierarchical bench
    tier; this exercises everything AROUND it that a 10M-object deployment
    leans on: bulk assign_batch placement, O(1) lookups, the per-node key
    index behind O(objects-on-node) clean_server, and the mover-only
    rebalance apply — asserting the directory stays exact (every object
    placed, only displaced objects move after churn).

    Last banked run (2026-07-30, 1-core CPU bench box, scaling mode):
    assign_batch(10M) 46 s (chunked greedy warm path), lookup_batch(10M)
    4.2 s, clean_server of 30 nodes 0.5 s total (per-node key index),
    collapsed rebalance + orphan re-seat 40.3 s with 307,200 orphans and
    zero extra moves, peak RSS 3.7 GB. This test caught two real bugs on
    first run: the unchunked 16.7M-row placement bucket (~100 GB of
    temps) and the fp32 sentinel-quota drift at bucket=2^24
    (_guard_sentinel_spill).
    """
    import resource
    import time as _time

    from rio_tpu.object_placement.jax_placement import JaxObjectPlacement

    n_objects, n_nodes = 10_485_760, 1_024
    # mode="scaling": full rebalances take the CLASS-COLLAPSED branch
    # (O(M^2) solve + O(N) expansion — no (N x M) anywhere), which is the
    # committed design for this scale; allocation still runs the chunked
    # greedy warm path. A greedy-mode full rebalance at 10M would scatter
    # into a dense (bucket x M) cost (~68 GB) by design — that mode is for
    # CPU-host deployments at directory sizes far below row 5.
    placement = JaxObjectPlacement(mode="scaling", node_axis_size=n_nodes)
    nodes = [f"10.9.{i // 256}.{i % 256}:9000" for i in range(n_nodes)]
    placement.sync_members(nodes)

    ids = [f"O.{i}" for i in range(n_objects)]
    t0 = _time.perf_counter()
    await placement.assign_batch(ids)
    assign_s = _time.perf_counter() - t0

    t0 = _time.perf_counter()
    where = await placement.lookup_batch(ids)
    lookup_s = _time.perf_counter() - t0
    assert all(w is not None for w in where)

    # Node-death churn: 30 nodes die; only their objects may move.
    dead = set(nodes[:30])
    before = dict(zip(ids, where))
    t0 = _time.perf_counter()
    for addr in dead:
        await placement.clean_server(addr)
    clean_s = _time.perf_counter() - t0
    orphans = [i for i in ids if before[i] in dead]

    placement.sync_members([n for n in nodes if n not in dead])
    t0 = _time.perf_counter()
    await placement.assign_batch(orphans)
    moved = await placement.rebalance()
    rebalance_s = _time.perf_counter() - t0

    after = await placement.lookup_batch(ids)
    stayed = sum(1 for i, w in zip(ids, after) if w == before[i])
    assert all(w is not None and w not in dead for w in after)
    # Displaced share ~3%; everything else must not have moved beyond the
    # rebalance's own (move-cost-guarded) churn.
    assert stayed >= n_objects - len(orphans) - moved
    rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
    print(
        f"assign={assign_s:.1f}s lookup={lookup_s:.1f}s clean={clean_s:.1f}s "
        f"rebalance={rebalance_s:.1f}s moved={moved} orphans={len(orphans)} "
        f"rss={rss_mb:.0f}MB"
    )
