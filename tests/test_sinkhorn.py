"""Solver tests: Sinkhorn marginals, greedy balance, sharded-vs-single parity.

Runs on the virtual 8-device CPU mesh from ``conftest.py`` (the same
mechanism the driver's ``dryrun_multichip`` uses).
"""

import jax
import jax.numpy as jnp
import numpy as np

from rio_tpu.ops import (
    assign_from_potentials,
    build_cost_matrix,
    greedy_balanced_assign,
    sinkhorn,
    sinkhorn_assign,
)
from rio_tpu.parallel import make_mesh, shard_cost, sharded_sinkhorn_assign


def _random_cost(n_obj, n_nodes, seed=0):
    key = jax.random.PRNGKey(seed)
    return jax.random.uniform(key, (n_obj, n_nodes), jnp.float32)


def test_sinkhorn_marginals_converge():
    cost = _random_cost(256, 16)
    mass = jnp.ones((256,))
    cap = jnp.ones((16,))
    res = sinkhorn(cost, mass, cap, eps=0.05, n_iters=100)
    assert float(res.err) < 1e-2
    assert np.isfinite(np.asarray(res.f)).all()
    assert np.isfinite(np.asarray(res.g)).all()


def test_sinkhorn_dead_nodes_attract_nothing():
    cost = _random_cost(128, 8)
    mass = jnp.ones((128,))
    cap = jnp.asarray([1, 1, 1, 1, 0, 0, 1, 1], jnp.float32)
    assignment, res = sinkhorn_assign(cost, mass, cap, eps=0.05, n_iters=60)
    assignment = np.asarray(assignment)
    assert not np.any(np.isin(assignment, [4, 5]))
    assert np.isneginf(np.asarray(res.g)[[4, 5]]).all()


def test_sinkhorn_balances_load():
    # Uniform cost: mass should spread ~evenly over nodes.
    cost = _random_cost(1024, 8, seed=3) * 0.01
    assignment, _ = sinkhorn_assign(
        cost, jnp.ones((1024,)), jnp.ones((8,)), eps=0.02, n_iters=80
    )
    counts = np.bincount(np.asarray(assignment), minlength=8)
    assert counts.max() <= 2.0 * 1024 / 8  # no node more than 2x fair share


def test_padding_rows_are_inert():
    cost = _random_cost(128, 8)
    mass = jnp.concatenate([jnp.ones((100,)), jnp.zeros((28,))])
    res = sinkhorn(cost, mass, jnp.ones((8,)), eps=0.05, n_iters=60)
    assert np.isneginf(np.asarray(res.f)[100:]).all()


def test_greedy_balanced_assign_spreads():
    cost = jnp.zeros((800, 8))
    assignment = greedy_balanced_assign(cost, jnp.ones((800,)), jnp.ones((8,)))
    counts = np.bincount(np.asarray(assignment), minlength=8)
    assert counts.max() <= 2 * 100
    assert counts.min() >= 50  # waterfilling is near-exactly balanced


def test_greedy_accounts_for_existing_load():
    # Node 0 already carries 100; incoming 60 should land elsewhere.
    cost = jnp.zeros((60, 4))
    load = jnp.asarray([100.0, 0.0, 0.0, 0.0])
    assignment = np.asarray(
        greedy_balanced_assign(cost, jnp.ones((60,)), jnp.ones((4,)), load)
    )
    assert not np.any(assignment == 0)


def test_greedy_respects_dead_nodes():
    load = jnp.zeros((8,))
    cap = jnp.ones((8,))
    alive = jnp.asarray([1, 1, 0, 1, 1, 1, 1, 1], jnp.float32)
    cost = jnp.broadcast_to(build_cost_matrix(load, cap, alive), (64, 8))
    assignment = np.asarray(
        greedy_balanced_assign(cost, jnp.ones((64,)), cap * alive)
    )
    assert not np.any(assignment == 2)


def test_assign_from_potentials_matches_full_solve():
    cost = _random_cost(256, 16, seed=7)
    mass = jnp.ones((256,))
    cap = jnp.ones((16,))
    assignment, res = sinkhorn_assign(cost, mass, cap, eps=0.05, n_iters=80)
    incr = assign_from_potentials(cost, res.g)
    np.testing.assert_array_equal(np.asarray(assignment), np.asarray(incr))


def test_sharded_matches_single_device():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    mesh = make_mesh()
    n_obj, n_nodes = 512, 32  # divisible by both mesh axis sizes
    cost = _random_cost(n_obj, n_nodes, seed=11)
    mass = jnp.ones((n_obj,))
    cap = jnp.ones((n_nodes,))

    single, _ = sinkhorn_assign(cost, mass, cap, eps=0.05, n_iters=40)
    sharded = sharded_sinkhorn_assign(
        mesh, shard_cost(mesh, cost), mass, cap, eps=0.05, n_iters=40
    )
    np.testing.assert_array_equal(np.asarray(single), np.asarray(sharded))


def test_mesh_factorization():
    mesh = make_mesh()
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("obj", "node")


def test_rounding_quantiles_ignore_padding():
    """Regression: quantile rounding must rank over REAL rows only.

    130 identical objects padded to a 256-row bucket across 4 equal nodes
    once yielded loads ~64/64/2/0 because padding rows stretched the
    quantile range; correct behavior is ~33 objects per node.
    """
    import jax.numpy as jnp
    import numpy as np

    from rio_tpu.ops import plan_rounded_assign, sinkhorn

    n_real, bucket, n_nodes = 130, 256, 4
    cost = jnp.zeros((bucket, n_nodes), jnp.float32)
    mass = jnp.concatenate(
        [jnp.ones((n_real,), jnp.float32), jnp.zeros((bucket - n_real,), jnp.float32)]
    )
    cap = jnp.ones((n_nodes,), jnp.float32)
    res = sinkhorn(cost, mass, cap, eps=0.05, n_iters=30)
    assignment = np.asarray(plan_rounded_assign(cost, res.f, res.g, 0.05))[:n_real]
    loads = np.bincount(assignment, minlength=n_nodes)
    assert loads.sum() == n_real
    assert loads.max() - loads.min() <= 2, loads


def test_route_hop_simulation_beats_reference_policy():
    """BASELINE acceptance: >=20% lower p99 hops than the random-pick policy."""
    from rio_tpu.utils.routing_sim import simulate_route_hops

    stats = simulate_route_hops(
        n_objects=100_000, n_nodes=100, n_requests=30_000, seed=7
    )
    ref, ours = stats["reference"], stats["rio_tpu"]
    assert ours.p99 <= 0.8 * ref.p99
    assert ours.mean < ref.mean
    # Determinism: same seed, same numbers.
    again = simulate_route_hops(
        n_objects=100_000, n_nodes=100, n_requests=30_000, seed=7
    )
    assert again["reference"].as_dict() == ref.as_dict()


def test_exact_quota_repair_minimal_moves_and_exact_loads():
    """Repair hits integer quotas exactly, moving only the excess.

    CDF rounding matches the soft marginals in expectation only (binomial
    noise, ~+3 sigma on the max column); the repair must land every column
    exactly on its largest-remainder quota while keeping >90% of objects
    where they were, and leave dead columns empty.
    """
    import numpy as np

    from rio_tpu.ops import (
        exact_quota_repair,
        plan_rounded_assign_from_scaling,
        scaling_core,
    )

    n, m = 16384, 64
    cost = jax.random.uniform(jax.random.PRNGKey(2), (n, m), jnp.float32)
    mass = jnp.ones((n,))
    cap = jnp.ones((m,)).at[7].set(0.0)  # one dead column
    u, v, K, _ = scaling_core(cost, mass, cap, eps=0.05, n_iters=30)
    idx = plan_rounded_assign_from_scaling(K, u, v)
    expected = cap / jnp.sum(cap) * n
    fixed = np.asarray(exact_quota_repair(idx, expected))
    loads = np.bincount(fixed, minlength=m)
    fair = n // (m - 1)
    assert loads[7] == 0
    live = np.delete(loads, 7)
    assert live.max() - live.min() <= 1  # largest-remainder exactness
    assert abs(int(live.max()) - fair) <= 1
    changed = (np.asarray(idx) != fixed).mean()
    assert changed < 0.10, f"repair moved {changed:.1%} of objects"
