"""Unit tests for the fault-injection subsystem (rio_tpu/faults.py).

Covers the schedule's determinism contract (same seed + same call sequence
=> same fault pattern), scripted and time-window outages, hang/heal
parking, the storage-trait wrappers' gating and health accounting, and the
transport layer's directional link verdicts.
"""

import asyncio

import pytest

from rio_tpu.cluster.storage import LocalStorage, Member
from rio_tpu.errors import Disconnect
from rio_tpu.faults import (
    FaultRule,
    FaultSchedule,
    FaultyMembershipStorage,
    FaultyObjectPlacement,
    FaultyReminderStorage,
    InjectedFault,
    LinkRule,
    OutageWindow,
    StorageHealth,
    TransportFaults,
)
from rio_tpu.journal import FAULT, Journal
from rio_tpu.object_placement import (
    LocalObjectPlacement,
    ObjectId,
    ObjectPlacementItem,
)
from rio_tpu.reminders import LocalReminderStorage, Reminder


# ---------------------------------------------------------------------------
# FaultSchedule
# ---------------------------------------------------------------------------


def _decisions(seed: int, n: int = 64) -> list[tuple[float, bool, bool]]:
    s = FaultSchedule(
        seed=seed,
        rules=[FaultRule(op="placement.*", error_rate=0.3, jitter=0.01)],
    )
    return [s.decide("placement.lookup") for _ in range(n)]


def test_schedule_is_deterministic_per_seed():
    assert _decisions(7) == _decisions(7)
    assert _decisions(7) != _decisions(8)  # astronomically unlikely to match


def test_rules_match_by_fnmatch_pattern():
    s = FaultSchedule(rules=[FaultRule(op="membership.*", error_rate=1.0)])
    assert s.decide("membership.members")[1] is True
    assert s.decide("placement.lookup")[1] is False


def test_fail_all_and_heal_script_total_outages():
    s = FaultSchedule()
    assert not s.is_down("membership.members")
    s.fail_all("membership.*")
    assert s.is_down("membership.members")
    assert not s.is_down("placement.lookup")
    _, fail, hang = s.decide("membership.push")
    assert fail and not hang
    s.heal()
    assert not s.is_down("membership.members")
    assert s.decide("membership.push") == (0.0, False, False)


def test_outage_window_runs_on_injected_clock():
    t = [0.0]
    s = FaultSchedule(outages=[OutageWindow(start=1.0, end=2.0)], clock=lambda: t[0])
    s.start()
    assert not s.is_down("placement.lookup")
    t[0] = 1.5
    assert s.is_down("placement.lookup")
    assert s.decide("placement.lookup")[1] is True
    t[0] = 2.5
    assert not s.is_down("placement.lookup")


@pytest.mark.asyncio
async def test_perturb_raises_and_counts():
    s = FaultSchedule(rules=[FaultRule(op="x", error_rate=1.0)])
    with pytest.raises(InjectedFault) as ei:
        await s.perturb("x")
    assert ei.value.op == "x"
    assert s.injected_errors == 1
    await s.perturb("unrelated")  # no rule -> no-op
    assert s.injected_errors == 1


@pytest.mark.asyncio
async def test_hang_parks_until_heal():
    s = FaultSchedule()
    s.fail_all("*", hang=True)
    parked = asyncio.ensure_future(s.perturb("membership.members"))
    await asyncio.sleep(0.05)
    assert not parked.done(), "hang did not park the call"
    s.heal()
    await asyncio.wait_for(parked, 1.0)
    assert s.injected_hangs == 1


def test_apply_sync_degrades_hang_to_error():
    s = FaultSchedule()
    s.fail_all("*", hang=True)
    with pytest.raises(InjectedFault):
        s.apply_sync("pg.execute")
    s.heal()
    s.apply_sync("pg.execute")  # healthy: no-op


def test_disabled_schedule_is_a_noop():
    s = FaultSchedule(rules=[FaultRule(error_rate=1.0)])
    s.enabled = False
    assert s.decide("anything") == (0.0, False, False)


def test_schedule_journals_fault_edges():
    j = Journal(capacity=16, node="t")
    s = FaultSchedule(journal=j)
    s.fail_all("membership.*")
    s.heal()
    kinds = [(ev.kind, ev.attrs.get("action")) for ev in j.events()]
    assert (FAULT, "fail_all") in kinds
    assert (FAULT, "heal") in kinds


# ---------------------------------------------------------------------------
# Storage wrappers
# ---------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_faulty_membership_wrapper_delegates_and_injects():
    health = StorageHealth()
    s = FaultSchedule()
    storage = FaultyMembershipStorage(LocalStorage(), s, health)
    await storage.push(Member.from_address("h1:1", active=True))
    assert [m.address for m in await storage.active_members()] == ["h1:1"]
    assert health.ops >= 2 and health.errors == 0

    s.fail_all("membership.*")
    with pytest.raises(InjectedFault):
        await storage.members()
    assert health.errors == 1 and health.injected == 1
    s.heal()
    assert len(await storage.members()) == 1


@pytest.mark.asyncio
async def test_faulty_placement_wrapper_full_surface():
    s = FaultSchedule()
    p = FaultyObjectPlacement(LocalObjectPlacement(), s)
    oid = ObjectId("Svc", "a")
    await p.update(ObjectPlacementItem(object_id=oid, server_address="h1:1"))
    assert await p.lookup(oid) == "h1:1"
    assert await p.lookup_batch([oid]) == ["h1:1"]
    await p.set_standbys(oid, ["h2:2"])
    assert await p.standbys(oid) == (["h2:2"], 0)
    await p.clean_server("h1:1")
    assert await p.lookup(oid) is None
    # outage hits only the placement trait
    s.fail_all("placement.*")
    with pytest.raises(InjectedFault):
        await p.items()


@pytest.mark.asyncio
async def test_faulty_reminder_wrapper_keeps_shard_surface():
    s = FaultSchedule()
    inner = LocalReminderStorage(num_shards=4)
    r = FaultyReminderStorage(inner, s)
    assert r.num_shards == 4
    await r.upsert(
        Reminder(
            object_kind="Svc", object_id="a", reminder_name="tick",
            period=1.0, next_due=0.0,
        )
    )
    assert len(await r.due(r.shard_for("Svc", "a"), now=1.0)) == 1
    s.fail_all("reminders.due")
    with pytest.raises(InjectedFault):
        await r.due(0, now=1.0)
    await r.get_lease(0)  # other reminder ops unaffected


@pytest.mark.asyncio
async def test_wrapper_getattr_exposes_inner_extensions():
    p = FaultyObjectPlacement(LocalObjectPlacement(), FaultSchedule())
    # Duck-typed provider probes (hasattr in the service layer / daemons)
    # must see exactly the inner object's surface.
    assert not hasattr(p, "sync_members")
    assert hasattr(p, "lookup_batch")


@pytest.mark.asyncio
async def test_real_backend_errors_count_without_injected_flag():
    class Exploding(LocalStorage):
        async def members(self):
            raise RuntimeError("disk on fire")

    health = StorageHealth()
    storage = FaultyMembershipStorage(Exploding(), FaultSchedule(), health)
    with pytest.raises(RuntimeError):
        await storage.members()
    assert health.errors == 1 and health.injected == 0
    assert "disk on fire" in health.last_error


def test_storage_health_degraded_edges():
    h = StorageHealth()
    # First error per source flips the edge; repeats do not.
    assert h.note_error("m.x", RuntimeError("a"), source="gossip") is True
    assert h.note_error("m.y", RuntimeError("b"), source="gossip") is False
    assert h.degraded
    assert h.note_error("p.z", RuntimeError("c"), source="service") is True
    assert h.note_ok("gossip") is True
    assert h.note_ok("gossip") is False
    assert h.degraded  # service still down
    assert h.note_ok("service") is True
    assert not h.degraded
    g = h.gauges()
    assert g["rio.storage.errors"] == 3.0
    assert g["rio.storage.degraded_sources"] == 0.0


@pytest.mark.asyncio
async def test_disabled_schedule_swaps_wrappers_to_passthrough():
    """``enabled = False`` re-arms wrappers into zero-cost passthrough:
    the inner backend's bound methods shadow the gated class methods, so
    a disabled wrapper adds no coroutine and counts nothing. Re-enabling
    restores the gates (and scripted outages fire again)."""
    inner = LocalObjectPlacement()
    s = FaultSchedule()
    health = StorageHealth()
    p = FaultyObjectPlacement(inner, s, health)
    oid = ObjectId("Svc", "a")
    await p.update(ObjectPlacementItem(object_id=oid, server_address="h1:1"))
    assert health.ops == 1  # enabled (idle) wrappers count

    s.enabled = False
    assert p.__dict__["lookup"] == inner.lookup  # swap active
    assert await p.lookup(oid) == "h1:1"
    assert health.ops == 1, "disabled passthrough must not count ops"

    s.enabled = True
    assert "lookup" not in p.__dict__  # gates restored
    s.fail_all("placement.*")
    with pytest.raises(InjectedFault):
        await p.lookup(oid)
    s.heal()
    assert await p.lookup(oid) == "h1:1"


@pytest.mark.asyncio
async def test_wrapper_built_on_disabled_schedule_starts_passthrough():
    s = FaultSchedule()
    s.enabled = False
    m = FaultyMembershipStorage(LocalStorage(), s)
    assert "members" in m.__dict__
    await m.push(Member.from_address("h1:1", active=True))
    assert [x.address for x in await m.active_members()] == ["h1:1"]


# ---------------------------------------------------------------------------
# Transport faults
# ---------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_partition_is_directional():
    tf = TransportFaults()
    tf.partition("a:1", "b:2")
    with pytest.raises(OSError):
        await tf.connect_gate("a:1", "b:2")
    await tf.connect_gate("b:2", "a:1")  # reverse link flows
    await tf.connect_gate("a:1", "c:3")  # unrelated link flows
    assert tf.connects_blocked == 1
    tf.heal()
    await tf.connect_gate("a:1", "b:2")


@pytest.mark.asyncio
async def test_symmetric_partition_blocks_both_ways():
    tf = TransportFaults()
    tf.partition("a:1", "b:2", symmetric=True)
    for src, dst in (("a:1", "b:2"), ("b:2", "a:1")):
        with pytest.raises(OSError):
            await tf.connect_gate(src, dst)


@pytest.mark.asyncio
async def test_heal_removes_only_matching_rules():
    tf = TransportFaults()
    tf.partition("a:1", "b:2")
    tf.partition("a:1", "c:3")
    tf.heal(src="a:1", dst="b:2")
    await tf.connect_gate("a:1", "b:2")
    with pytest.raises(OSError):
        await tf.connect_gate("a:1", "c:3")


class _StubConn:
    def __init__(self):
        self.closed = False
        self.pending = 0
        self.delivered = 0
        self.frames: list[bytes] = []

    async def roundtrip(self, frame: bytes) -> bytes:
        self.frames.append(frame)
        return b"ok:" + frame

    def write(self, frame: bytes) -> None:
        self.frames.append(frame)

    def close(self) -> None:
        self.closed = True


@pytest.mark.asyncio
async def test_faulty_conn_drop_closes_and_disconnects():
    tf = TransportFaults()
    inner = _StubConn()
    conn = tf.wrap_conn(inner, "a:1", "b:2")
    assert await conn.roundtrip(b"x") == b"ok:x"  # healthy passthrough
    tf.add_rule(LinkRule(src="a:1", dst="b:2", drop=1.0))
    with pytest.raises(Disconnect):
        await conn.roundtrip(b"y")
    assert inner.closed, "dropped frame must close the underlying conn"
    assert tf.frames_dropped == 1
    assert inner.frames == [b"x"], "the dropped frame must never reach the wire"


@pytest.mark.asyncio
async def test_faults_demo_smoke():
    from rio_tpu.faults import _demo

    gauges = await _demo()
    assert gauges["rio.faults.errors"] >= 1.0
    assert gauges["rio.transport_faults.connects_blocked"] >= 1.0
