"""Backend chaos matrix: seeded fault sweeps over every storage backend.

Satellite 2 glue test: the scriptable error/latency/outage modes added to
``tests/fake_pg.py`` (DBAPI-level, ``pg.*`` ops) and ``tests/fake_redis.py``
(wire-level, ``redis.*`` ops) plus the trait-level wrappers must all drive
the same invariant on every backend: under a fixed-seed error rate, a
retrying caller converges to exactly the acked state — injected failures
are loud (the retry sees them) but never corrupting (a failed write either
fully lands or fully doesn't).

The tier-1 run covers one seed per backend; the ``slow`` sweep runs the
full seed matrix (nightly chaos lane).
"""

import asyncio

import pytest

from rio_tpu.cluster.storage import Member, MembershipStorage
from rio_tpu.cluster.storage.sqlite import SqliteMembershipStorage
from rio_tpu.faults import FaultRule, FaultSchedule, FaultyMembershipStorage
from rio_tpu.object_placement import ObjectId, ObjectPlacement, ObjectPlacementItem
from rio_tpu.object_placement.sqlite import SqliteObjectPlacement

FAST_SEEDS = (7,)
FULL_SEEDS = (7, 23, 1999, 31337)


async def _retry(coro_fn, attempts: int = 50):
    """Drive one storage op to success through injected failures."""
    last: BaseException | None = None
    for _ in range(attempts):
        try:
            return await coro_fn()
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — injected or backend error
            last = e
            await asyncio.sleep(0)
    raise AssertionError(f"op never succeeded through retries: {last!r}")


async def _chaos_workload(
    members: MembershipStorage, placement: ObjectPlacement, keys: int = 12
) -> None:
    """Acked-state convergence under churn: every op is retried to ack,
    then the final read must reflect exactly the acked writes."""
    await _retry(members.prepare)
    await _retry(placement.prepare)
    for i in range(keys):
        addr = f"10.0.0.{i}:5000"
        await _retry(lambda a=addr: members.push(Member.from_address(a, active=True)))
        oid = ObjectId("Svc", f"k{i}")
        await _retry(
            lambda o=oid, a=addr: placement.update(
                ObjectPlacementItem(object_id=o, server_address=a)
            )
        )
    # Interleave reads (they fail/retry too) with targeted mutations.
    for i in range(0, keys, 3):
        await _retry(lambda i=i: members.set_inactive("10.0.0.%d" % i, 5000))
        await _retry(lambda i=i: placement.remove(ObjectId("Svc", f"k{i}")))

    active = await _retry(members.active_members)
    assert {m.address for m in active} == {
        f"10.0.0.{i}:5000" for i in range(keys) if i % 3 != 0
    }
    for i in range(keys):
        owner = await _retry(lambda i=i: placement.lookup(ObjectId("Svc", f"k{i}")))
        assert owner == (None if i % 3 == 0 else f"10.0.0.{i}:5000")


# ---------------------------------------------------------------------------
# sqlite — trait-level wrappers
# ---------------------------------------------------------------------------


async def _sqlite_case(tmp_path, seed: int) -> None:
    schedule = FaultSchedule(
        seed=seed, rules=[FaultRule(op="*", error_rate=0.25)]
    )
    members = FaultyMembershipStorage(
        SqliteMembershipStorage(str(tmp_path / f"m{seed}.db")), schedule
    )
    from rio_tpu.faults import FaultyObjectPlacement

    placement = FaultyObjectPlacement(
        SqliteObjectPlacement(str(tmp_path / f"p{seed}.db")), schedule
    )
    await _chaos_workload(members, placement)
    assert schedule.injected_errors > 0, "the sweep injected nothing"


@pytest.mark.asyncio
async def test_sqlite_chaos_fixed_seed(tmp_path):
    for seed in FAST_SEEDS:
        await _sqlite_case(tmp_path, seed)


@pytest.mark.slow
@pytest.mark.asyncio
async def test_sqlite_chaos_seed_sweep(tmp_path):
    for seed in FULL_SEEDS:
        await _sqlite_case(tmp_path, seed)


# ---------------------------------------------------------------------------
# fake-pg — DBAPI-level injection (pg.* ops through apply_sync)
# ---------------------------------------------------------------------------


async def _pg_case(seed: int) -> None:
    from tests import fake_pg

    fake_pg.install()
    fake_pg.reset()
    from rio_tpu.cluster.storage.postgres import PostgresMembershipStorage
    from rio_tpu.object_placement.postgres import PostgresObjectPlacement

    schedule = FaultSchedule(
        seed=seed, rules=[FaultRule(op="pg.execute", error_rate=0.15)]
    )
    dsn = f"postgresql://fake-pg/chaos{seed}"
    members = PostgresMembershipStorage(dsn)
    placement = PostgresObjectPlacement(dsn)
    # Prepare cleanly, then inject at the statement level underneath the
    # REAL Postgres backends — their rollback/recovery paths execute.
    await members.prepare()
    await placement.prepare()
    fake_pg.set_faults(schedule)
    try:
        await _chaos_workload(members, placement)
        assert schedule.injected_errors > 0
    finally:
        fake_pg.set_faults(None)
        fake_pg.reset()


@pytest.mark.asyncio
async def test_fake_pg_chaos_fixed_seed():
    for seed in FAST_SEEDS:
        await _pg_case(seed)


@pytest.mark.slow
@pytest.mark.asyncio
async def test_fake_pg_chaos_seed_sweep():
    for seed in FULL_SEEDS:
        await _pg_case(seed)


# ---------------------------------------------------------------------------
# fake-redis — wire-level injection (redis.* ops, -ERR replies)
# ---------------------------------------------------------------------------


async def _redis_case(seed: int, *, reset_conn: bool = False) -> None:
    from rio_tpu.cluster.storage.redis import RedisMembershipStorage
    from rio_tpu.object_placement.redis import RedisObjectPlacement
    from rio_tpu.utils.resp import RedisClient

    from .fake_redis import FakeRedisServer

    server = await FakeRedisServer().start()
    try:
        client = RedisClient("127.0.0.1", server.port)
        members = RedisMembershipStorage(client, key_prefix=f"chaos{seed}_m")
        placement = RedisObjectPlacement(client, key_prefix=f"chaos{seed}_p")
        schedule = FaultSchedule(
            seed=seed, rules=[FaultRule(op="redis.*", error_rate=0.1)]
        )
        server.set_faults(schedule, reset_conn=reset_conn)
        await _chaos_workload(members, placement)
        assert schedule.injected_errors > 0
        server.set_faults(None)
        client.close()
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_fake_redis_chaos_fixed_seed():
    for seed in FAST_SEEDS:
        await _redis_case(seed)


@pytest.mark.slow
@pytest.mark.asyncio
async def test_fake_redis_chaos_seed_sweep():
    for seed in FULL_SEEDS:
        await _redis_case(seed)


@pytest.mark.asyncio
async def test_fake_redis_chaos_connection_resets():
    """``reset_conn`` mode: injected faults close the socket instead of
    replying -ERR — the client pool's reconnect path carries the load."""
    for seed in FAST_SEEDS:
        await _redis_case(seed, reset_conn=True)
