"""ReminderStorage backend matrix: the generic trait-level checks of
``tests/test_backends.py`` extended to the reminder store — every backend
(local / sqlite / postgres-over-fake-pg / redis-over-fake-redis) runs the
SAME assertions over its production code path."""

import os

import pytest

from rio_tpu.reminders import (
    LocalReminderStorage,
    Reminder,
    ReminderStorage,
    shard_of,
)
from rio_tpu.reminders.sqlite import SqliteReminderStorage
from rio_tpu.utils.resp import RedisClient

from .fake_redis import FakeRedisServer


def test_shard_of_is_stable_and_bounded():
    # The cluster-wide agreement hinge: same inputs → same shard, always.
    assert shard_of("Kind", "id-1", 32) == shard_of("Kind", "id-1", 32)
    seen = {shard_of("K", str(i), 8) for i in range(200)}
    assert seen <= set(range(8))
    assert len(seen) > 1  # actually spreads


async def check_reminders(s: ReminderStorage):
    await s.prepare()
    kind, oid = "Player", "p1"
    shard = s.shard_for(kind, oid)

    # upsert stamps the shard; list enumerates per object, name-ordered
    await s.upsert(Reminder(kind, oid, "b-save", period=5.0, next_due=100.0))
    await s.upsert(Reminder(kind, oid, "a-expire", period=2.0, next_due=50.0))
    await s.upsert(Reminder(kind, "p2", "other", period=9.0, next_due=60.0))
    rows = await s.list_object(kind, oid)
    assert [r.reminder_name for r in rows] == ["a-expire", "b-save"]
    assert all(r.shard == shard for r in rows)

    # re-registering overwrites (Orleans semantics)
    await s.upsert(Reminder(kind, oid, "b-save", period=7.0, next_due=140.0))
    rows = await s.list_object(kind, oid)
    assert [(r.period, r.next_due) for r in rows] == [(2.0, 50.0), (7.0, 140.0)]

    # due scan: one shard, next_due <= now, soonest first, limit honored
    due = await s.due(shard, now=141.0)
    mine = [r for r in due if (r.object_kind, r.object_id) == (kind, oid)]
    assert [r.reminder_name for r in mine] == ["a-expire", "b-save"]
    assert [r.reminder_name for r in await s.due(shard, now=99.0)
            if (r.object_kind, r.object_id) == (kind, oid)] == ["a-expire"]
    limited = await s.due(shard, now=141.0, limit=1)
    assert len(limited) == 1
    assert not [r for r in await s.due(shard, now=10.0)
                if (r.object_kind, r.object_id) == (kind, oid)]

    # reschedule advances next_due (the post-delivery step)
    await s.reschedule(kind, oid, "a-expire", 500.0)
    assert not [r for r in await s.due(shard, now=499.0)
                if r.reminder_name == "a-expire"]
    assert (await s.list_object(kind, oid))[0].next_due == 500.0

    # shard_counts reflects live rows
    counts = await s.shard_counts()
    assert counts[shard] >= 2
    assert sum(counts.values()) == 3

    # remove one / remove the whole object
    await s.remove(kind, oid, "a-expire")
    assert [r.reminder_name for r in await s.list_object(kind, oid)] == ["b-save"]
    await s.remove_object(kind, oid)
    assert await s.list_object(kind, oid) == []
    assert [r.reminder_name for r in await s.list_object(kind, "p2")] == ["other"]
    await s.remove_object(kind, "p2")
    assert await s.shard_counts() == {}


async def check_leases(s: ReminderStorage):
    await s.prepare()
    shard = 3
    # fresh acquisition
    l1 = await s.acquire_lease(shard, "n1:1", ttl=10.0, now=1000.0)
    assert l1 is not None and l1.owner == "n1:1" and l1.expires_at == 1010.0
    # blocked while another owner's lease is unexpired
    assert await s.acquire_lease(shard, "n2:2", 10.0, now=1005.0) is None
    # renewal keeps the epoch, extends the TTL
    l2 = await s.acquire_lease(shard, "n1:1", 10.0, now=1005.0)
    assert l2 is not None and l2.epoch == l1.epoch and l2.expires_at == 1015.0
    # expired takeover bumps the epoch (the fencing token)
    l3 = await s.acquire_lease(shard, "n2:2", 10.0, now=1020.0)
    assert l3 is not None and l3.owner == "n2:2" and l3.epoch > l1.epoch
    # a stale release (old owner + old epoch) must not disturb the new lease
    await s.release_lease(shard, "n1:1", l1.epoch)
    g = await s.get_lease(shard)
    assert g is not None and g.owner == "n2:2" and g.expires_at > 1020.0
    # the owner's own release frees the shard immediately
    await s.release_lease(shard, "n2:2", l3.epoch)
    l4 = await s.acquire_lease(shard, "n3:3", 10.0, now=1021.0)
    assert l4 is not None and l4.owner == "n3:3" and l4.epoch > l3.epoch
    # independent shards don't interfere
    other = await s.acquire_lease(shard + 1, "n1:1", 10.0, now=1021.0)
    assert other is not None and other.epoch == 1


@pytest.mark.asyncio
async def test_local_reminder_storage():
    await check_reminders(LocalReminderStorage())
    await check_leases(LocalReminderStorage())


@pytest.mark.asyncio
async def test_sqlite_reminder_storage(tmp_path):
    await check_reminders(SqliteReminderStorage(str(tmp_path / "rem.db")))
    await check_leases(SqliteReminderStorage(str(tmp_path / "lease.db")))


@pytest.mark.asyncio
async def test_postgres_reminder_storage():
    """Real server when RIO_TPU_PG_DSN is set, else the DBAPI fake — the
    portable SQL, paramstyle translation, and thread bridge run either way."""
    from rio_tpu.reminders.postgres import PostgresReminderStorage
    from rio_tpu.utils.pg import driver_available

    dsn = os.environ.get("RIO_TPU_PG_DSN", "")
    if not driver_available() or not dsn:
        from tests import fake_pg

        fake_pg.install()
        fake_pg.reset()
        dsn = "postgresql://fake-pg/reminders"
    await check_reminders(PostgresReminderStorage(dsn))
    await check_leases(PostgresReminderStorage(dsn))


@pytest.mark.asyncio
async def test_redis_reminder_storage():
    from rio_tpu.reminders.redis import RedisReminderStorage

    server = await FakeRedisServer().start()
    try:
        client = RedisClient("127.0.0.1", server.port)
        await check_reminders(RedisReminderStorage(client, key_prefix="t_rem"))
        await check_leases(RedisReminderStorage(client, key_prefix="t_lease"))
        # key-prefix isolation
        other = RedisReminderStorage(client, key_prefix="t_isolated")
        assert await other.shard_counts() == {}
        client.close()
    finally:
        await server.stop()
