"""Tier-1 smoke: the observability example runs end-to-end, SDK or not.

``examples/observability.py`` must work in a bare environment: when the
optional OpenTelemetry packages are absent it degrades to an in-memory
metric exporter (same surface as a periodic OTLP push) instead of
crashing — and either way the journal scrape at the end reconstructs the
demo migration. The operator CLI's ``--demo`` mode rides the same boot
path; both are exercised here exactly as tier-1 CI runs them.
"""

import asyncio
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "examples"))


def test_observability_example_end_to_end():
    import observability as demo

    result = asyncio.run(demo.main(n_requests=20))
    # No-SDK fallback (or the real exporter when the env has the packages).
    assert result["otlp_mode"] in ("in-memory", "otlp")
    if result["otlp_mode"] == "in-memory":
        assert result["snapshots"] == 2  # one gauge snapshot per node
    assert result["spans"] > 0
    # Journal scrape: merged tail saw events, explain reconstructed the
    # migrated worker's history, and at least one row links to a trace.
    assert result["tail"] > 0
    assert result["explain"] >= 5  # assign + pin/snapshot/install(s)/flip
    assert result["traces"] >= 1
    # Trend plane: every node answered DumpSeries with a real window.
    assert result["series_nodes"] == 2
    assert result["series_samples"] > 0


def test_admin_cli_demo_modes(capsys):
    from rio_tpu.admin import _cli_main

    assert asyncio.run(_cli_main(["--demo", "tail"])) == 0
    out = capsys.readouterr().out
    assert "migrate_pin" in out and "[tail]" in out

    assert asyncio.run(_cli_main(["--demo", "explain"])) == 0
    out = capsys.readouterr().out
    assert "linked trace(s)" in out and "migrate_flip" in out

    assert asyncio.run(_cli_main(["--demo", "stats"])) == 0
    out = capsys.readouterr().out
    assert "journal=" in out and "events=" in out
