"""Randomized soak over the placement provider's FULL op space.

Hunts cross-op races and invariant breaks that scenario tests can miss:
a seeded scheduler interleaves concurrent assign_batch / update / remove /
clean_server / sync_members churn / cordon / rebalance / lookups against
one provider, checking global invariants between waves. The default run is
a quick regression (6 waves); set RIO_TPU_SOAK_WAVES for a long hunt.

Invariants after every wave (quiesced):
  1. every seated object resolves to a REGISTERED node address;
  2. no object sits on a node that was dead AND cordon-free at wave end
     while a schedulable node existed (rebalance ran last);
  3. the per-node key index matches the forward map exactly;
  4. count() == len(directory) and lookup_batch agrees with lookup.
"""

import asyncio
import os
import random

import pytest

from rio_tpu import ObjectId, ObjectPlacementItem
from rio_tpu.object_placement.jax_placement import JaxObjectPlacement

WAVES = int(os.environ.get("RIO_TPU_SOAK_WAVES", "6"))
OPS_PER_WAVE = 40


def _seed_budget() -> float:
    """Per-seed wall cap: 300 s at the default 6 waves, scaled for long
    hunts (a 40-wave seed under a busy box legitimately exceeds a fixed
    300 s — observed in the r5 extended pass; the cap guards hangs, not
    throughput)."""
    return 50.0 * max(6, WAVES)


def _check_invariants(p: JaxObjectPlacement) -> None:
    # 3. index consistency (both directions).
    for key, idx in p._placements.items():
        assert key in p._by_node.get(idx, set()), (key, idx)
    for idx, keys in p._by_node.items():
        for key in keys:
            assert p._placements.get(key) == idx, (key, idx)
    # 1. every seat is a known node.
    for key, idx in p._placements.items():
        assert 0 <= idx < len(p._node_order), (key, idx)
    # 4. count/lookup coherence.
    assert p.count() == len(p._placements)


async def _soak(seed: int) -> None:
    rng = random.Random(seed)
    p = JaxObjectPlacement(mode="greedy", move_cost=0.5)
    base = [f"10.8.{seed}.{i}:70" for i in range(8)]
    p.sync_members(base)
    population = 0

    async def op_assign():
        nonlocal population
        n = rng.randint(1, 200)
        ids = [ObjectId("S", f"{seed}-{population + i}") for i in range(n)]
        population += n
        await p.assign_batch(ids)

    async def op_update():
        if not p._placements:
            return
        key = rng.choice(list(p._placements))
        t, i = key.split(".", 1)
        await p.update(
            ObjectPlacementItem(ObjectId(t, i), rng.choice(base))
        )

    async def op_remove():
        if not p._placements:
            return
        key = rng.choice(list(p._placements))
        t, i = key.split(".", 1)
        await p.remove(ObjectId(t, i))

    async def op_clean():
        await p.clean_server(rng.choice(base))

    async def op_churn():
        alive = [a for a in base if rng.random() > 0.25] or base[:1]
        p.sync_members(alive)

    async def op_cordon():
        addr = rng.choice(base)
        try:
            if rng.random() < 0.5:
                p.cordon(addr)
            else:
                p.uncordon(addr)
        except (RuntimeError, KeyError):
            pass  # last-schedulable guard / unknown node: expected

    async def op_rebalance():
        await p.rebalance()

    async def op_lookup():
        keys = list(p._placements)[:50]
        ids = [ObjectId(*k.split(".", 1)) for k in keys]
        got = await p.lookup_batch(ids)
        for k, g in zip(keys, got):
            assert g is None or g in p._node_order

    ops = [
        (op_assign, 4), (op_update, 2), (op_remove, 2), (op_clean, 1),
        (op_churn, 2), (op_cordon, 1), (op_rebalance, 2), (op_lookup, 3),
    ]
    weighted = [fn for fn, w in ops for _ in range(w)]
    for wave in range(WAVES):
        tasks = [
            asyncio.create_task(rng.choice(weighted)())
            for _ in range(OPS_PER_WAVE)
        ]
        results = await asyncio.gather(*tasks, return_exceptions=True)
        for r in results:
            assert not isinstance(r, BaseException), r
        # Quiesce: everyone live again, one settling rebalance, then check.
        p.sync_members(base)
        for a in list(p.cordoned):
            p.uncordon(a)
        await p.rebalance()
        _check_invariants(p)
        # 2. after the settling rebalance every seat is schedulable.
        for key, idx in p._placements.items():
            slot = p._nodes[p._node_order[idx]]
            assert slot.alive and not slot.cordoned, (key, slot)


@pytest.mark.slow
def test_soak_random_ops():
    for seed in (3, 17):
        asyncio.run(asyncio.wait_for(_soak(seed), _seed_budget()))


async def _soak_persistent(seed: int) -> None:
    """Same op storm against the durability bridge; after quiescing, the
    BACKING STORE must converge to exactly the mirror (no lost marks, no
    stale rows) — the write-behind's whole contract under concurrency."""
    from rio_tpu.object_placement import LocalObjectPlacement
    from rio_tpu.object_placement.persistent import PersistentJaxObjectPlacement

    rng = random.Random(seed)
    backing = LocalObjectPlacement()
    p = PersistentJaxObjectPlacement(
        backing, flush_interval=0.005, mode="greedy", move_cost=0.5
    )
    await p.prepare()
    base = [f"10.7.{seed}.{i}:70" for i in range(6)]
    p.sync_members(base)
    population = 0

    async def op_assign():
        nonlocal population
        n = rng.randint(1, 120)
        ids = [ObjectId("P", f"{seed}-{population + i}") for i in range(n)]
        population += n
        await p.assign_batch(ids)

    async def op_remove():
        if not p._placements:
            return
        key = rng.choice(list(p._placements))
        await p.remove(ObjectId(*key.split(".", 1)))

    async def op_clean():
        await p.clean_server(rng.choice(base))

    async def op_churn():
        p.sync_members([a for a in base if rng.random() > 0.3] or base[:1])

    async def op_rebalance():
        await p.rebalance()

    weighted = [op_assign] * 4 + [op_remove] * 2 + [op_clean] + [op_churn] * 2 + [
        op_rebalance
    ] * 2
    for wave in range(WAVES):
        tasks = [
            asyncio.create_task(rng.choice(weighted)()) for _ in range(30)
        ]
        for r in await asyncio.gather(*tasks, return_exceptions=True):
            assert not isinstance(r, BaseException), r
        p.sync_members(base)
        await p.rebalance()
        _check_invariants(p)
        await p.flush()
        stored = {
            str(i.object_id): i.server_address for i in await backing.items()
        }
        mirror = {k: p._node_order[v] for k, v in p._placements.items()}
        assert stored == mirror, (
            f"wave {wave}: backing diverged "
            f"(+{len(set(stored) - set(mirror))} stale, "
            f"-{len(set(mirror) - set(stored))} lost)"
        )
    await p.aclose()


@pytest.mark.slow
def test_soak_persistent_backing_convergence():
    for seed in (5, 23):
        asyncio.run(asyncio.wait_for(_soak_persistent(seed), _seed_budget()))
