"""Two-level OT placement: quality, liveness, overflow, and mesh sharding."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rio_tpu.ops.sinkhorn import route_sentinel_spill
from rio_tpu.parallel import make_mesh
from rio_tpu.parallel.hierarchical import (
    hierarchical_assign,
    sharded_hierarchical_assign,
)


def _features(key, n, d, m):
    k1, k2 = jax.random.split(key)
    obj = jax.random.normal(k1, (n, d), jnp.float32)
    node = jax.random.normal(k2, (d, m), jnp.float32) * 0.2
    return obj, node


def test_hierarchical_balances_and_avoids_dead_nodes():
    n, d, m, g = 2048, 16, 64, 8
    obj, node = _features(jax.random.PRNGKey(0), n, d, m)
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32).at[10].set(0.0).at[37].set(0.0)
    res = hierarchical_assign(obj, node, cap, alive, n_groups=g)
    a = np.asarray(res.assignment)
    assert a.min() >= 0 and a.max() < m
    # dead nodes attract nothing
    assert not np.any(np.isin(a, [10, 37]))
    # load balance: capacity-constrained OT keeps every live node near fair
    counts = np.bincount(a, minlength=m)
    fair = n / 62
    assert counts[np.setdiff1d(np.arange(m), [10, 37])].max() < 2.2 * fair
    assert int(res.overflow) == 0


def test_hierarchical_respects_affinity():
    """Objects aligned with a group's direction should land in that group."""
    n, d, m, g = 512, 8, 32, 4
    s = m // g
    key = jax.random.PRNGKey(1)
    # Groups have a shared feature direction (rack locality); nodes are
    # small perturbations of their group's direction.
    group_dirs = jax.random.normal(key, (g, d), jnp.float32)
    node = (
        jnp.repeat(group_dirs, s, axis=0)
        + 0.1 * jax.random.normal(jax.random.PRNGKey(7), (m, d))
    ).T  # (d, m)
    owner = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, m)
    obj = node.T[owner] * 3.0
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32)
    res = hierarchical_assign(obj, node, cap, alive, n_groups=g, eps=0.05)
    owner_group = np.asarray(owner) // s
    got_group = np.asarray(res.group)
    # Capacity quotas cap the match rate at the owner-group histogram's
    # overlap with uniform quotas; 0.6 is comfortably below that.
    assert np.mean(got_group == owner_group) > 0.6
    assert int(res.overflow) == 0


def test_hierarchical_capacity_weighting():
    n, d, m, g = 1024, 8, 16, 4
    obj, node = _features(jax.random.PRNGKey(3), n, d, m)
    cap = jnp.ones((m,), jnp.float32).at[0:4].set(3.0)  # group 0 is 3x
    alive = jnp.ones((m,), jnp.float32)
    res = hierarchical_assign(obj, node, cap, alive, n_groups=g)
    counts = np.bincount(np.asarray(res.group), minlength=g)
    # group 0 holds ~3x the objects of the others (3/(3+1+1+1) = 0.5)
    assert counts[0] > 0.38 * n


def test_hierarchical_overflow_fallback():
    """A tiny bucket forces overflow; fallbacks stay on live nodes."""
    n, d, m, g = 256, 8, 16, 4
    obj, node = _features(jax.random.PRNGKey(4), n, d, m)
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32).at[0].set(0.0)
    res = hierarchical_assign(obj, node, cap, alive, n_groups=g, bucket=16)
    assert int(res.overflow) > 0
    a = np.asarray(res.assignment)
    assert a.min() >= 0 and a.max() < m
    assert not np.any(a == 0)  # dead node excluded even on fallback


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device mesh")
def test_sharded_hierarchical_on_mesh():
    n, d, m, g = 4096, 16, 64, 8
    obj, node = _features(jax.random.PRNGKey(5), n, d, m)
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32).at[3].set(0.0)
    mesh = make_mesh(jax.devices()[:8])
    res = sharded_hierarchical_assign(mesh, obj, node, cap, alive, n_groups=g)
    a = np.asarray(res.assignment)
    assert a.shape == (n,)
    assert a.min() >= 0 and a.max() < m
    assert not np.any(a == 3)
    counts = np.bincount(a, minlength=m)
    assert counts[np.setdiff1d(np.arange(m), [3])].max() < 2.5 * (n / 63)


def test_fine_stage_sentinel_spill_routes_to_live_member():
    """ADVICE r4: a real row seated on the padding-sentinel column (quota
    drift, or the repair's refill clip spilling into the last column) must
    NOT be clamped by take_along_axis onto member s-1 — it routes to the
    group's highest-capacity member, like the overflow fallback. The guard
    is the ONE shared implementation in ops.sinkhorn (also used by
    JaxObjectPlacement's bucket-shaped repair)."""
    s = 4  # group size; sentinel column index == s
    #          real rows on nodes, one real row spilled onto the sentinel,
    #          padding rows legitimately on the sentinel
    local = jnp.array([0, 2, s, s, s], jnp.int32)
    mass = jnp.array([1.0, 1.0, 1.0, 0.0, 0.0], jnp.float32)
    cap = jnp.array([1.0, 0.0, 1.0, 3.0], jnp.float32)  # member 1 dead, 3 biggest
    out = np.asarray(route_sentinel_spill(local, mass > 0, s, cap))
    assert out[0] == 0 and out[1] == 2  # untouched real rows
    assert out[2] == 3  # spilled real row -> argmax-capacity live member
    assert out[3] == s and out[4] == s  # padding keeps the sentinel


def test_hierarchical_dead_members_excluded_under_extreme_skew():
    """End-to-end guard exercise: groups whose capacity lives on ONE member
    (rest dead) stress the fine stage's quota/sentinel machinery; no real
    object may land on a dead node and every node stays in range."""
    n, d, m, g = 1024, 8, 32, 8
    obj, node = _features(jax.random.PRNGKey(11), n, d, m)
    s = m // g
    # In each group, only the first member is alive (capacity 4x to keep
    # group quotas equal); bucket sized for the skewed per-group share.
    alive = jnp.zeros((m,), jnp.float32).at[:: s].set(1.0)
    cap = jnp.ones((m,), jnp.float32) * 4.0
    res = hierarchical_assign(obj, node, cap, alive, n_groups=g, bucket=256)
    a = np.asarray(res.assignment)
    dead = np.asarray(alive) == 0.0
    assert not np.any(dead[a]), "object seated on a dead node"
    loads = np.bincount(a, minlength=m)
    assert loads[np.asarray(alive) > 0].sum() == n
    # Equal group capacities -> the g live members (one per group) carry
    # exact-quota fair shares of n, within largest-remainder rounding.
    live_loads = loads[np.asarray(alive) > 0]
    assert live_loads.max() - live_loads.min() <= 2, live_loads


@pytest.mark.slow
@pytest.mark.skipif(
    not os.environ.get("RIO_TPU_SCALE_MESH"),
    reason="opt-in (RIO_TPU_SCALE_MESH=1): 1M x 1024 on the 8-CPU mesh, minutes",
)
@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device mesh")
def test_sharded_hierarchical_1m_x_1024_on_mesh():
    """VERDICT r4 item 4: prove the sharding/memory math at the BASELINE
    row-5 node scale (1M objects x 1024 nodes, 32 groups) on the virtual
    mesh — four orders above the dryrun's phase-1 512 objects. Peak memory
    per shard stays O(N/8 x (G + S + d)) ~ 100 MB; a flat cost matrix
    would be 4 GB. Asserts the full quality contract: every object on a
    live node, zero overflow, exact-quota load spread, and the psum'd
    overflow counter consistent across shards."""
    n, d, m, g = 1_048_576, 16, 1024, 32
    obj, node = _features(jax.random.PRNGKey(21), n, d, m)
    cap = jnp.ones((m,), jnp.float32)
    dead = [5, 99, 640, 1023]
    alive = jnp.ones((m,), jnp.float32)
    for i in dead:
        alive = alive.at[i].set(0.0)
    mesh = make_mesh(jax.devices()[:8])
    res = sharded_hierarchical_assign(
        mesh, obj, node, cap, alive, n_groups=g, coarse_iters=16, fine_iters=16
    )
    jax.block_until_ready(res.assignment)
    a = np.asarray(res.assignment)
    assert a.shape == (n,)
    assert a.min() >= 0 and a.max() < m
    assert not np.any(np.isin(a, dead)), "object seated on a dead node"
    assert int(res.overflow) == 0
    loads = np.bincount(a, minlength=m)
    assert loads[dead].sum() == 0
    live_loads = loads[np.asarray(alive) > 0]
    fair = n / (m - len(dead))
    # Exact largest-remainder quotas per shard: global spread is bounded
    # by the summed per-shard roundings, far inside 10% of fair.
    assert live_loads.max() <= 1.1 * fair, (live_loads.max(), fair)
    assert live_loads.min() >= 0.9 * fair, (live_loads.min(), fair)


@pytest.mark.slow
@pytest.mark.skipif(
    os.environ.get("RIO_TPU_SCALE_MESH") != "full",
    reason="opt-in (RIO_TPU_SCALE_MESH=full): the FULL BASELINE row-5 shape, minutes + GBs",
)
@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device mesh")
def test_sharded_hierarchical_10m_x_1024_full_row5_shape():
    """BASELINE row 5 VERBATIM (10,485,760 objects x 1024 nodes, 32 groups)
    through the sharded two-level solve on the 8-device mesh. A flat cost
    matrix at this shape is 40 GB — the factorized solve's per-shard
    working set is ~0.5 GB, which is the entire point. Same quality
    contract as the 1M tier."""
    import time

    n, d, m, g = 10_485_760, 16, 1024, 32
    obj, node = _features(jax.random.PRNGKey(23), n, d, m)
    cap = jnp.ones((m,), jnp.float32)
    dead = [7, 300, 512, 900]
    alive = jnp.ones((m,), jnp.float32)
    for i in dead:
        alive = alive.at[i].set(0.0)
    mesh = make_mesh(jax.devices()[:8])
    t0 = time.monotonic()
    res = sharded_hierarchical_assign(
        mesh, obj, node, cap, alive, n_groups=g, coarse_iters=16, fine_iters=16
    )
    jax.block_until_ready(res.assignment)
    wall = time.monotonic() - t0
    a = np.asarray(res.assignment)
    assert a.shape == (n,)
    assert not np.any(np.isin(a, dead))
    assert int(res.overflow) == 0
    loads = np.bincount(a, minlength=m)
    assert loads[dead].sum() == 0
    live_loads = loads[np.asarray(alive) > 0]
    fair = n / (m - len(dead))
    assert live_loads.max() <= 1.1 * fair and live_loads.min() >= 0.9 * fair
    print(f"\n10M x 1024 sharded hierarchical: {wall:.1f}s on the CPU mesh, "
          f"load spread {live_loads.min()}-{live_loads.max()} (fair {fair:.0f})")


def test_hierarchical_exact_node_quotas():
    """Both stages repair to exact largest-remainder quotas: every live
    node lands within 1 of its capacity share (was ±20% rounding noise)."""
    n, d, m, g = 8192, 8, 64, 8
    obj, node = _features(jax.random.PRNGKey(9), n, d, m)
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32)
    res = hierarchical_assign(obj, node, cap, alive, n_groups=g)
    assert int(res.overflow) == 0
    loads = np.bincount(np.asarray(res.assignment), minlength=m)
    assert loads.max() - loads.min() <= 2  # group quota +-1, node quota +-1


def test_chunked_hierarchical_matches_flat_quality():
    """chunked_hierarchical_assign = the sharded design run temporally.

    Same contract the mesh version proves spatially: per-node loads exact
    to chunk granularity, dead nodes empty, zero overflow, and affinity
    quality on par with the flat solve (each chunk spreads over the same
    capacity proportions). This is the path that pins TPU compile cost to
    the chunk shape (v5e measured 599 s flat compile at 2.6M vs 50 s at
    the 655k chunk shape)."""
    from rio_tpu.parallel.hierarchical import chunked_hierarchical_assign

    n, d, m, g, chunks = 4096, 16, 64, 8, 4
    obj, node = _features(jax.random.PRNGKey(42), n, d, m)
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32).at[5].set(0.0).at[50].set(0.0)

    flat = hierarchical_assign(obj, node, cap, alive, n_groups=g)
    chunked = chunked_hierarchical_assign(
        obj, node, cap, alive, n_groups=g, n_chunks=chunks
    )
    a = np.asarray(chunked.assignment)
    assert a.min() >= 0 and a.max() < m
    assert not np.any(np.isin(a, [5, 50]))
    assert int(chunked.overflow) == 0
    # Load exactness to chunk granularity: every live node within
    # n_chunks of the flat solve's (exact-quota) load.
    cf = np.bincount(np.asarray(flat.assignment), minlength=m)
    cc = np.bincount(a, minlength=m)
    assert np.abs(cc - cf).max() <= chunks
    # Affinity quality: mean assigned score within 2% of the flat solve.
    on = np.asarray(obj @ node)
    q_flat = on[np.arange(n), np.asarray(flat.assignment)].mean()
    q_chunk = on[np.arange(n), a].mean()
    spread = on.std()
    assert q_chunk >= q_flat - 0.02 * spread


def test_chunked_timed_twin_matches_lax_map_form_exactly():
    """The host-loop twin (``chunked_hierarchical_assign_timed``) calls
    the SAME jitted per-chunk solve the ``lax.map`` form runs, so its
    outputs are bit-identical — and it yields the per-chunk wall timings
    SolveStats banks (ISSUE 11 solver telemetry)."""
    from rio_tpu.parallel.hierarchical import (
        chunked_hierarchical_assign,
        chunked_hierarchical_assign_timed,
    )

    n, d, m, g, chunks = 256, 8, 8, 4, 4
    obj, node = _features(jax.random.PRNGKey(7), n, d, m)
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32).at[3].set(0.0)

    mapped = chunked_hierarchical_assign(
        obj, node, cap, alive, n_groups=g, n_chunks=chunks
    )
    timed, chunk_ms = chunked_hierarchical_assign_timed(
        obj, node, cap, alive, n_groups=g, n_chunks=chunks
    )
    assert np.array_equal(np.asarray(mapped.assignment),
                          np.asarray(timed.assignment))
    assert np.array_equal(np.asarray(mapped.group), np.asarray(timed.group))
    assert int(mapped.overflow) == int(timed.overflow)
    assert len(chunk_ms) == chunks
    assert all(ms > 0.0 for ms in chunk_ms)
    # The first chunk pays the one-time compile — the compile-vs-execute
    # signal the telemetry wants is visible in the timings themselves.
    assert chunk_ms[0] >= max(chunk_ms[1:])


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device mesh")
def test_mesh_chunked_matches_flat_and_chunked_quality():
    """mesh x chunk = the sharded design AND the chunked design composed.

    Each of the n_shards * n_chunks cells solves its slice against
    1/(n_shards * n_chunks) of every node's capacity, so the same
    per-slice-independence argument that makes each parent match the flat
    solve applies to the composition: per-node loads exact to CELL
    granularity, dead nodes empty, zero overflow, affinity quality on par
    with both the flat and the chunked-only solve."""
    from rio_tpu.parallel.hierarchical import (
        chunked_hierarchical_assign,
        mesh_chunked_hierarchical_assign,
    )

    n, d, m, g, chunks = 16384, 16, 64, 8, 2
    obj, node = _features(jax.random.PRNGKey(42), n, d, m)
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32).at[5].set(0.0).at[50].set(0.0)
    mesh = make_mesh(jax.devices()[:8])
    cells = 8 * chunks

    flat = hierarchical_assign(obj, node, cap, alive, n_groups=g)
    chunked = chunked_hierarchical_assign(
        obj, node, cap, alive, n_groups=g, n_chunks=chunks
    )
    composed = mesh_chunked_hierarchical_assign(
        mesh, obj, node, cap, alive, n_groups=g, n_chunks=chunks
    )
    a = np.asarray(composed.assignment)
    assert a.shape == (n,)
    assert a.min() >= 0 and a.max() < m
    assert not np.any(np.isin(a, [5, 50]))
    assert int(composed.overflow) == 0
    # Load exactness to cell granularity (each cell repairs to exact
    # largest-remainder quotas of its slice).
    cf = np.bincount(np.asarray(flat.assignment), minlength=m)
    cm = np.bincount(a, minlength=m)
    assert np.abs(cm - cf).max() <= cells
    # Quality within 2% of a cost-spread of BOTH parents (calibrated:
    # measured gaps 0.007/0.010 spreads at this shape).
    on = np.asarray(obj @ node)
    q_flat = on[np.arange(n), np.asarray(flat.assignment)].mean()
    q_chunk = on[np.arange(n), np.asarray(chunked.assignment)].mean()
    q_mesh = on[np.arange(n), a].mean()
    spread = on.std()
    assert q_mesh >= q_flat - 0.02 * spread, (q_mesh, q_flat, spread)
    assert q_mesh >= q_chunk - 0.02 * spread, (q_mesh, q_chunk, spread)
    # The composed solve returns REPLICATED finite coarse potentials (the
    # warm seed the placement layer persists into PlanState).
    cg = np.asarray(composed.coarse_g)
    assert cg.shape == (g,) and np.isfinite(cg).all()


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device mesh")
def test_mesh_chunked_survives_wide_cost_ranges():
    """The per-row gauge shift must survive the composition: with raw
    affinities scaled 1000x (cost-range/eps >> 88, the regime where a
    GLOBAL shift underflows tail rows and the solve silently diverges —
    CLAUDE.md r3), the composed solve still balances, excludes the dead
    node, and matches flat quality."""
    from rio_tpu.parallel.hierarchical import mesh_chunked_hierarchical_assign

    n, d, m, g, chunks = 8192, 16, 32, 4, 2
    obj, node = _features(jax.random.PRNGKey(3), n, d, m)
    obj = obj * 1e3
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32).at[7].set(0.0)
    mesh = make_mesh(jax.devices()[:8])

    res = mesh_chunked_hierarchical_assign(
        mesh, obj, node, cap, alive, n_groups=g, n_chunks=chunks
    )
    a = np.asarray(res.assignment)
    assert not np.any(a == 7)
    assert int(res.overflow) == 0
    counts = np.bincount(a, minlength=m)
    live = np.setdiff1d(np.arange(m), [7])
    fair = n / len(live)
    assert counts[live].min() >= 0.9 * fair and counts[live].max() <= 1.1 * fair
    flat = hierarchical_assign(obj, node, cap, alive, n_groups=g)
    on = np.asarray(obj @ node)
    q_flat = on[np.arange(n), np.asarray(flat.assignment)].mean()
    q_mesh = on[np.arange(n), a].mean()
    assert q_mesh >= q_flat - 0.02 * on.std(), (q_mesh, q_flat)
    assert np.isfinite(np.asarray(res.coarse_g)).all()


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device mesh")
def test_mesh_chunked_timed_twin_matches_lax_map_form_exactly():
    """The host-loop twin dispatches each chunk's mesh-wide slab through
    the SAME cell solve (identical single-step ``cap / (shards * chunks)``
    division, exact row->cell mapping), so assignment/group/overflow are
    bit-identical to the ``lax.map`` form — and the per-chunk wall timings
    expose the first-chunk compile for SolveStats."""
    from rio_tpu.parallel.hierarchical import (
        mesh_chunked_hierarchical_assign,
        mesh_chunked_hierarchical_assign_timed,
    )

    n, d, m, g, chunks = 2048, 8, 8, 4, 4
    obj, node = _features(jax.random.PRNGKey(7), n, d, m)
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32).at[3].set(0.0)
    mesh = make_mesh(jax.devices()[:8])

    mapped = mesh_chunked_hierarchical_assign(
        mesh, obj, node, cap, alive, n_groups=g, n_chunks=chunks
    )
    timed, chunk_ms = mesh_chunked_hierarchical_assign_timed(
        mesh, obj, node, cap, alive, n_groups=g, n_chunks=chunks
    )
    assert np.array_equal(np.asarray(mapped.assignment),
                          np.asarray(timed.assignment))
    assert np.array_equal(np.asarray(mapped.group), np.asarray(timed.group))
    assert int(mapped.overflow) == int(timed.overflow)
    assert len(chunk_ms) == chunks
    assert all(ms > 0.0 for ms in chunk_ms)
    # First chunk pays the one-time cell compile.
    assert chunk_ms[0] >= max(chunk_ms[1:])
    # Warm-seed roundtrip: feeding the replicated potentials back in is
    # accepted by the same cached executable (no retrace on cold/warm flip)
    # and still yields a valid solve.
    timed2, chunk_ms2 = mesh_chunked_hierarchical_assign_timed(
        mesh, obj, node, cap, alive,
        n_groups=g, n_chunks=chunks, coarse_g_init=timed.coarse_g,
    )
    assert not np.any(np.asarray(timed2.assignment) == 3)
    assert int(timed2.overflow) == 0
    # Cached executable: the warm re-solve's first chunk pays no compile.
    assert chunk_ms2[0] < chunk_ms[0]


@pytest.mark.slow
@pytest.mark.skipif(
    not os.environ.get("RIO_TPU_SCALE_MESH"),
    reason="opt-in (RIO_TPU_SCALE_MESH=1): 10M x 1024 composed solve, minutes",
)
@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device mesh")
def test_mesh_chunked_10m_x_1024_compile_pinned_and_parity():
    """ISSUE 18 acceptance rung: 10,485,760 x 1024 through the composed
    mesh x chunk solve on the 8-virtual-device CPU mesh.

    The point of the composition is that compile cost pins to the CELL
    shape, not N: the 1M rung (8 shards x 2 chunks) and the 10M rung
    (8 shards x 20 chunks) use the SAME 65,536-row cell, so the 10M first
    chunk's compile must come in flat — within 1.5x of the 1M rung's
    (each rung compiles its own executable: the capacity scale constant
    differs, so this measures a genuine fresh compile at matched shape).
    Quality is checked against the chunked-only solve at the SAME N via a
    sampled transport-cost ratio (mean best-minus-assigned affinity
    regret) <= 1.05."""
    from rio_tpu.parallel.hierarchical import (
        chunked_hierarchical_assign,
        mesh_chunked_hierarchical_assign_timed,
    )

    d, m, g = 16, 1024, 32
    cell = 65_536
    mesh = make_mesh(jax.devices()[:8])
    cap = jnp.ones((m,), jnp.float32)
    dead = [7, 300, 512, 900]
    alive = jnp.ones((m,), jnp.float32)
    for i in dead:
        alive = alive.at[i].set(0.0)
    kw = dict(coarse_iters=16, fine_iters=16)

    # Rung A: 1M = 8 shards x 2 chunks x 65,536-row cells (cold compile).
    n1 = 8 * 2 * cell
    obj1, node = _features(jax.random.PRNGKey(23), n1, d, m)
    res1, ms1 = mesh_chunked_hierarchical_assign_timed(
        mesh, obj1, node, cap, alive, n_groups=g, n_chunks=2, **kw
    )
    assert int(res1.overflow) == 0

    # Rung B: 10M = 8 shards x 20 chunks x the SAME cell shape.
    n10 = 8 * 20 * cell
    obj10, _ = _features(jax.random.PRNGKey(29), n10, d, m)
    res10, ms10 = mesh_chunked_hierarchical_assign_timed(
        mesh, obj10, node, cap, alive, n_groups=g, n_chunks=20, **kw
    )
    a = np.asarray(res10.assignment)
    assert a.shape == (n10,)
    assert not np.any(np.isin(a, dead))
    assert int(res10.overflow) == 0
    loads = np.bincount(a, minlength=m)
    live_loads = loads[np.asarray(alive) > 0]
    fair = n10 / (m - len(dead))
    assert live_loads.max() <= 1.1 * fair and live_loads.min() >= 0.9 * fair
    # THE acceptance gate: first-chunk compile flat in N.
    assert ms10[0] <= 1.5 * ms1[0], (ms10[0], ms1[0])
    # Steady-state chunks never recompile.
    assert max(ms10[1:]) < ms10[0], (ms10[0], max(ms10[1:]))

    # Chunked-only comparator at matched N (single-chip dispatch shape:
    # 20 chunks of 524,288 rows = _HIER_CHUNK_ROWS).
    comp = chunked_hierarchical_assign(
        obj10, node, cap, alive, n_groups=g, n_chunks=20, **kw
    )
    ac = np.asarray(comp.assignment)
    # Sampled transport cost: mean regret (best live affinity minus the
    # assigned affinity) over a fixed 65,536-row sample — the (N x M)
    # affinity matrix at 10M x 1024 would be 40 GB, the sample is 256 MB.
    idx = np.arange(0, n10, n10 // 65_536)[:65_536]
    on_s = np.asarray(obj10[idx] @ node)
    on_s_live = np.where(np.asarray(alive)[None, :] > 0, on_s, -np.inf)
    best = on_s_live.max(axis=1)
    cost_mesh = float(np.mean(best - on_s[np.arange(len(idx)), a[idx]]))
    cost_chunk = float(np.mean(best - on_s[np.arange(len(idx)), ac[idx]]))
    assert cost_mesh <= 1.05 * cost_chunk, (cost_mesh, cost_chunk)
    print(f"\n10M x 1024 mesh x chunk: first-chunk {ms10[0]:.0f} ms "
          f"(1M rung {ms1[0]:.0f} ms), steady {np.median(ms10[1:]):.0f} ms, "
          f"transport-cost ratio {cost_mesh / cost_chunk:.4f}")
