"""Two-level OT placement: quality, liveness, overflow, and mesh sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rio_tpu.parallel import make_mesh
from rio_tpu.parallel.hierarchical import (
    hierarchical_assign,
    sharded_hierarchical_assign,
)


def _features(key, n, d, m):
    k1, k2 = jax.random.split(key)
    obj = jax.random.normal(k1, (n, d), jnp.float32)
    node = jax.random.normal(k2, (d, m), jnp.float32) * 0.2
    return obj, node


def test_hierarchical_balances_and_avoids_dead_nodes():
    n, d, m, g = 2048, 16, 64, 8
    obj, node = _features(jax.random.PRNGKey(0), n, d, m)
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32).at[10].set(0.0).at[37].set(0.0)
    res = hierarchical_assign(obj, node, cap, alive, n_groups=g)
    a = np.asarray(res.assignment)
    assert a.min() >= 0 and a.max() < m
    # dead nodes attract nothing
    assert not np.any(np.isin(a, [10, 37]))
    # load balance: capacity-constrained OT keeps every live node near fair
    counts = np.bincount(a, minlength=m)
    fair = n / 62
    assert counts[np.setdiff1d(np.arange(m), [10, 37])].max() < 2.2 * fair
    assert int(res.overflow) == 0


def test_hierarchical_respects_affinity():
    """Objects aligned with a group's direction should land in that group."""
    n, d, m, g = 512, 8, 32, 4
    s = m // g
    key = jax.random.PRNGKey(1)
    # Groups have a shared feature direction (rack locality); nodes are
    # small perturbations of their group's direction.
    group_dirs = jax.random.normal(key, (g, d), jnp.float32)
    node = (
        jnp.repeat(group_dirs, s, axis=0)
        + 0.1 * jax.random.normal(jax.random.PRNGKey(7), (m, d))
    ).T  # (d, m)
    owner = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, m)
    obj = node.T[owner] * 3.0
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32)
    res = hierarchical_assign(obj, node, cap, alive, n_groups=g, eps=0.05)
    owner_group = np.asarray(owner) // s
    got_group = np.asarray(res.group)
    # Capacity quotas cap the match rate at the owner-group histogram's
    # overlap with uniform quotas; 0.6 is comfortably below that.
    assert np.mean(got_group == owner_group) > 0.6
    assert int(res.overflow) == 0


def test_hierarchical_capacity_weighting():
    n, d, m, g = 1024, 8, 16, 4
    obj, node = _features(jax.random.PRNGKey(3), n, d, m)
    cap = jnp.ones((m,), jnp.float32).at[0:4].set(3.0)  # group 0 is 3x
    alive = jnp.ones((m,), jnp.float32)
    res = hierarchical_assign(obj, node, cap, alive, n_groups=g)
    counts = np.bincount(np.asarray(res.group), minlength=g)
    # group 0 holds ~3x the objects of the others (3/(3+1+1+1) = 0.5)
    assert counts[0] > 0.38 * n


def test_hierarchical_overflow_fallback():
    """A tiny bucket forces overflow; fallbacks stay on live nodes."""
    n, d, m, g = 256, 8, 16, 4
    obj, node = _features(jax.random.PRNGKey(4), n, d, m)
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32).at[0].set(0.0)
    res = hierarchical_assign(obj, node, cap, alive, n_groups=g, bucket=16)
    assert int(res.overflow) > 0
    a = np.asarray(res.assignment)
    assert a.min() >= 0 and a.max() < m
    assert not np.any(a == 0)  # dead node excluded even on fallback


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device mesh")
def test_sharded_hierarchical_on_mesh():
    n, d, m, g = 4096, 16, 64, 8
    obj, node = _features(jax.random.PRNGKey(5), n, d, m)
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32).at[3].set(0.0)
    mesh = make_mesh(jax.devices()[:8])
    res = sharded_hierarchical_assign(mesh, obj, node, cap, alive, n_groups=g)
    a = np.asarray(res.assignment)
    assert a.shape == (n,)
    assert a.min() >= 0 and a.max() < m
    assert not np.any(a == 3)
    counts = np.bincount(a, minlength=m)
    assert counts[np.setdiff1d(np.arange(m), [3])].max() < 2.5 * (n / 63)


def test_hierarchical_exact_node_quotas():
    """Both stages repair to exact largest-remainder quotas: every live
    node lands within 1 of its capacity share (was ±20% rounding noise)."""
    n, d, m, g = 8192, 8, 64, 8
    obj, node = _features(jax.random.PRNGKey(9), n, d, m)
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32)
    res = hierarchical_assign(obj, node, cap, alive, n_groups=g)
    assert int(res.overflow) == 0
    loads = np.bincount(np.asarray(res.assignment), minlength=m)
    assert loads.max() - loads.min() <= 2  # group quota +-1, node quota +-1
