"""Control-plane flight recorder: ring semantics + the chaos acceptance bar.

The integration test is ISSUE 9's acceptance criterion: a replicated actor
is activated, migrated (all four phases under one explicit trace), its new
primary is hard-killed mid-traffic, and the standby promotes — and the
merged journal, scraped over the wire from the survivors, reconstructs the
full causal history gap-free: per-node seqs monotonic and contiguous,
migration phases in order and sharing one trace id, promotion after the
flip and linked to a captured request span.
"""

import asyncio

import pytest

from rio_tpu import (
    AdminCommand,
    AppData,
    Registry,
    ServiceObject,
    handler,
    message,
    tracing,
)
from rio_tpu.admin import ADMIN_TYPE, DumpEvents, EventsSnapshot, explain
from rio_tpu.journal import (
    MEMBER_DOWN,
    MIGRATE_FLIP,
    MIGRATE_INSTALL,
    MIGRATE_PIN,
    MIGRATE_SNAPSHOT,
    PLACE_ASSIGN,
    REPLICA_PROMOTE,
    REPLICA_SEAT,
    Journal,
    JournalEvent,
    format_event,
    merge_events,
    subject_key,
)
from rio_tpu.commands import ServerInfo
from rio_tpu.registry import ObjectId
from rio_tpu.replication import ReplicationConfig
from rio_tpu.state import LocalState, StateProvider, managed_state

from .server_utils import Cluster, run_integration_test


@pytest.fixture(autouse=True)
def _clean_tracing():
    tracing.clear_sinks()
    tracing.set_sample_rate(0.0)
    yield
    tracing.clear_sinks()
    tracing.set_sample_rate(0.0)


# ---------------------------------------------------------------------------
# Ring semantics
# ---------------------------------------------------------------------------


def test_record_is_sequential_and_bounded():
    j = Journal(capacity=8, node="n1")
    for i in range(5):
        ev = j.record("solve", moved=i)
        assert ev.seq == i + 1
        assert ev.node == "n1"
    assert j.recorded == 5
    assert len(j) == 5
    assert j.dropped == 0
    assert [e.seq for e in j.events()] == [1, 2, 3, 4, 5]


def test_ring_overflow_counts_drops_and_never_fails():
    j = Journal(capacity=8, node="n1")
    for i in range(20):
        ev = j.record("place_assign", f"T/{i}")
        assert ev.seq == i + 1  # record always succeeds, even when full
    assert j.recorded == 20
    assert j.dropped == 12  # 20 recorded - 8 slots
    assert len(j) == 8
    # The NEWEST capacity-many events survive, oldest → newest.
    assert [e.seq for e in j.events()] == list(range(13, 21))
    assert j.gauges()["rio.journal.dropped"] == 12.0


def test_events_filters_and_tail_limit():
    j = Journal(capacity=64, node="n1")
    for i in range(10):
        j.record("place_assign" if i % 2 == 0 else "solve", f"T/{i % 3}")
    assert len(j.events(kinds=["solve"])) == 5
    assert all(e.kind == "solve" for e in j.events(kinds=["solve"]))
    by_key = j.events(key="T/0")
    assert [e.key for e in by_key] == ["T/0"] * len(by_key)
    # since_seq is exclusive (resume from the last seq you saw).
    assert [e.seq for e in j.events(since_seq=7)] == [8, 9, 10]
    # limit keeps the NEWEST matches — a tail, not a head.
    assert [e.seq for e in j.events(limit=3)] == [8, 9, 10]
    assert [e.seq for e in j.events(kinds=["solve"], limit=2)] == [8, 10]


def test_row_round_trip_and_tolerant_decode():
    j = Journal(capacity=4, node="a:1")
    ev = j.record("migrate_pin", "W/w0", epoch=3, target="b:2")
    back = JournalEvent.from_row(ev.to_row())
    assert back == ev
    # Short legacy row: missing trailing fields decode to defaults.
    short = JournalEvent.from_row([7, 1.5, 2.5, "a:1", 0, "solve"])
    assert (short.seq, short.kind, short.key, short.attrs, short.trace_id) == (
        7, "solve", "", {}, None,
    )
    # Longer future row: extra trailing fields ignored; garbage attrs → {}.
    future = JournalEvent.from_row(
        [1, 1.0, 1.0, "n", 0, "k", "K", "not-a-dict", 42, "future-field"]
    )
    assert future.attrs == {}
    assert future.trace_id is None  # non-str trace slot tolerated


def test_merge_preserves_per_node_order_under_wall_ties():
    a, b = Journal(capacity=8, node="a"), Journal(capacity=8, node="b")
    for i in range(3):
        a.record("solve", moved=i)
        b.record("solve", moved=i)
    evs = merge_events([a.events(), b.events()])
    # Pin identical wall clocks to force the tie-break path.
    for e in evs:
        e.wall_ts = 100.0
    evs = merge_events([[e for e in evs if e.node == "a"],
                        [e for e in evs if e.node == "b"]])
    for node in ("a", "b"):
        seqs = [e.seq for e in evs if e.node == node]
        assert seqs == sorted(seqs)  # per-node order survives the merge


def test_record_captures_active_trace():
    spans = []
    tracing.add_sink(spans.append)
    j = Journal(capacity=4, node="n")
    assert j.record("solve").trace_id is None  # no active span
    with tracing.span("drive"):
        ev = j.record("migrate_pin", "W/w0")
        inside = tracing.current_trace_id()
    assert ev.trace_id == inside and inside is not None
    assert spans[-1].trace_id == inside
    line = format_event(ev)
    assert "migrate_pin" in line and "W/w0" in line and inside in line


# ---------------------------------------------------------------------------
# Chaos: migrate, kill the new primary mid-traffic, promote — then explain
# ---------------------------------------------------------------------------

ACTIVE: dict[str, str] = {}


@message
class JAdd:
    amount: int = 0


@message
class JTotals:
    total: int = 0
    hot: int = 0
    address: str = ""


@message
class JLedgerState:
    total: int = 0


class JLedger(ServiceObject):
    __replicated__ = True

    state = managed_state(JLedgerState)

    def __init__(self):
        self.hot = 0

    def __migrate_state__(self):
        return {"hot": self.hot}

    def __restore_state__(self, value):
        self.hot = int(value["hot"])

    @handler
    async def add(self, msg: JAdd, ctx: AppData) -> JTotals:
        self.state.total += msg.amount
        self.hot += msg.amount
        await self.save_state(ctx)
        return JTotals(
            total=self.state.total, hot=self.hot, address=ctx.get(ServerInfo).address
        )


def build_registry() -> Registry:
    return Registry().add_type(JLedger)


async def _wait_dead(cluster: Cluster, address: str, timeout: float = 10.0) -> None:
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if not await cluster.members.is_active(address):
            return
        await asyncio.sleep(0.02)
    raise TimeoutError(f"{address} never went inactive")


def test_chaos_journal_reconstructs_migration_and_promotion():
    state = LocalState()
    spans = []

    async def body(cluster: Cluster):
        tracing.set_sample_rate(1.0)
        tracing.add_sink(spans.append)
        client = cluster.client()
        try:
            subject = subject_key("JLedger", "L1")
            out = await client.send(JLedger, "L1", JAdd(amount=1), returns=JTotals)
            source_addr = out.address
            for _ in range(4):
                out = await client.send(JLedger, "L1", JAdd(amount=1), returns=JTotals)
            source = next(
                s for s in cluster.servers if s.local_address == source_addr
            )
            # Let the replication daemon seat the standby before migrating.
            for _ in range(100):
                held, _ = await cluster.placement.standbys(ObjectId("JLedger", "L1"))
                if held:
                    break
                await asyncio.sleep(0.05)
            assert held and source_addr not in held

            # Drive the handoff inside an explicit span: every source-side
            # phase (pin → snapshot → install → flip) must share its trace.
            target_addr = next(
                s.local_address
                for s in cluster.servers
                if s.local_address != source_addr and s.local_address not in held
            )
            with tracing.span("chaos_migrate") as sp:
                migrate_trace = sp.trace_id
                ok = await source.migration_manager.migrate_out(
                    ObjectId("JLedger", "L1"), target_addr
                )
            assert ok

            # Traffic lands on the new primary; ship-on-ack re-arms the
            # standby with post-migration state.
            acked = 5
            for _ in range(5):
                out = await client.send(JLedger, "L1", JAdd(amount=1), returns=JTotals)
                acked += 1
            assert out.address == target_addr
            for _ in range(100):
                held2, _ = await cluster.placement.standbys(ObjectId("JLedger", "L1"))
                if held2 and target_addr not in held2:
                    break
                await asyncio.sleep(0.05)
            assert held2 and target_addr not in held2

            # Kill the new primary hard, mid-conversation.
            target_srv = next(
                s for s in cluster.servers if s.local_address == target_addr
            )
            target_srv.admin_sender().send(AdminCommand.server_exit())
            await _wait_dead(cluster, target_addr)

            for _ in range(3):
                out = await client.send(JLedger, "L1", JAdd(amount=1), returns=JTotals)
                acked += 1
            assert out.total == acked  # promotion kept every acked write

            # --- the journal acceptance assertions ---

            # Per-node seqs are monotonic AND contiguous (gap-free), and the
            # ring never dropped: recording is bounded but nothing spilled.
            for s in cluster.servers:
                assert s.journal is not None
                seqs = [e.seq for e in s.journal.events()]
                assert seqs == list(range(seqs[0], seqs[0] + len(seqs)))
                assert s.journal.dropped == 0

            # Wire-scraped explain over the SURVIVORS reconstructs the full
            # causal history (the dead node's rows died with it; the
            # source-side install row keeps the chain complete).
            survivors = [
                s.local_address
                for s in cluster.servers
                if s.local_address != target_addr
            ]
            history = await explain(client, survivors, "JLedger", "L1")
            kinds = [e.kind for e in history]
            assert PLACE_ASSIGN in kinds
            for k in (MIGRATE_PIN, MIGRATE_SNAPSHOT, MIGRATE_INSTALL,
                      MIGRATE_FLIP, REPLICA_PROMOTE):
                assert kinds.count(k) >= 1, k
            order = [kinds.index(k) for k in
                     (MIGRATE_PIN, MIGRATE_SNAPSHOT, MIGRATE_FLIP)]
            assert order == sorted(order)
            assert kinds.index(MIGRATE_FLIP) < kinds.index(REPLICA_PROMOTE)
            assert kinds.index(PLACE_ASSIGN) < kinds.index(MIGRATE_PIN)

            # One shared trace across the migration hops: every source-side
            # phase row carries the explicit span's trace id.
            phase_traces = {
                e.trace_id for e in history
                if e.kind in (MIGRATE_PIN, MIGRATE_SNAPSHOT, MIGRATE_FLIP)
            }
            assert phase_traces == {migrate_trace}

            # The promotion ran inside a traced request: its row joins the
            # captured request spans on trace_id.
            promote = next(e for e in history if e.kind == REPLICA_PROMOTE)
            assert promote.trace_id is not None
            assert promote.trace_id in {s.trace_id for s in spans}
            assert promote.attrs.get("new_primary") == out.address
            assert promote.attrs.get("dead") == target_addr

            # Seat churn was journaled too (standby (re)assignments).
            all_events = merge_events(
                [s.journal.events() for s in cluster.servers]
            )
            assert any(e.kind == REPLICA_SEAT and e.key == subject for e in all_events)

            # Resumable tail over the wire: since_seq excludes what we saw.
            snap = await client.send(
                ADMIN_TYPE,
                survivors[0],
                DumpEvents(key=subject),
                returns=EventsSnapshot,
            )
            assert snap.node_seq >= max((e.seq for e in snap.events()), default=0)
            resumed = await client.send(
                ADMIN_TYPE,
                survivors[0],
                DumpEvents(key=subject, since_seq=snap.node_seq),
                returns=EventsSnapshot,
            )
            assert resumed.rows == []
        finally:
            client.close()

    async def wrapped(cluster: Cluster):
        for s in cluster.servers:
            s.app_data.set(state, as_type=StateProvider)
        await body(cluster)

    asyncio.run(
        run_integration_test(
            wrapped,
            registry_builder=build_registry,
            num_servers=3,
            server_kwargs={
                "replication_config": ReplicationConfig(
                    k=1, anti_entropy_interval=0.3, seat_ttl=0.3
                )
            },
        )
    )
