"""In-process DBAPI fake standing in for ``psycopg`` (cf. fake_redis.py).

The environment ships no PostgreSQL driver or server, but dead code is
worse than a fake: this module lets the REAL Postgres backends
(``rio_tpu/{cluster/storage,object_placement,state}/postgres.py``) and the
REAL ``PgDb`` helper execute their full logic in the default suite — DSN
connection handling, the ``?``→``%s`` paramstyle translation (translated
back to qmark here, so a broken translation produces broken SQL and fails
loudly), the cursor context-manager protocol, ``description``-gated
fetches, commit/rollback, and the thread bridge — everything above the PG
wire protocol itself. The SQL dialect the backends use (``ON CONFLICT …
DO UPDATE``, ``DOUBLE PRECISION``) is executed by sqlite, which accepts
both.

Usage::

    from tests.fake_pg import install
    install()           # registers this module as `psycopg`
    PgDb("postgresql://fake/db")   # resolves the fake driver
"""

from __future__ import annotations

import sqlite3
import sys
import threading

# One shared sqlite engine per DSN, so multiple "connections" to the same
# DSN see the same data (the backend matrix shares one DSN across the
# membership/placement/state providers, like a real database would).
_ENGINES: dict[str, sqlite3.Connection] = {}
_ENGINES_LOCK = threading.Lock()
_EXEC_LOCK = threading.RLock()  # serialize all statements on the shared engine

# Scriptable fault injection (rio_tpu.faults.FaultSchedule | None): when
# set, every connect() and statement execution consults the schedule
# synchronously (these run on executor threads via PgDb's asyncio.to_thread
# bridge — ``apply_sync`` sleeps/raises there without touching the loop).
# Ops: "pg.connect", "pg.execute", "pg.commit". Chaos tests script outages
# here to prove the REAL Postgres backends ride the resilience paths.
_FAULTS = None


def set_faults(schedule) -> None:
    """Install (or clear, with None) the module-wide fault schedule."""
    global _FAULTS
    _FAULTS = schedule


def _perturb(op: str) -> None:
    if _FAULTS is not None:
        try:
            _FAULTS.apply_sync(op)
        except Exception as e:
            raise Error(f"injected: {e}") from e


class Error(Exception):
    """DBAPI base error (psycopg.Error stand-in)."""


def _qmark(sql: str) -> str:
    """``%s`` placeholders → ``?`` (outside string literals) for sqlite."""
    out: list[str] = []
    in_str = False
    i = 0
    while i < len(sql):
        ch = sql[i]
        if ch == "'":
            in_str = not in_str
        if not in_str and ch == "%" and sql[i + 1 : i + 2] == "s":
            out.append("?")
            i += 2
            continue
        out.append(ch)
        i += 1
    return "".join(out)


class FakeCursor:
    def __init__(self, engine: sqlite3.Connection) -> None:
        self._cur = engine.cursor()

    def __enter__(self) -> "FakeCursor":
        return self

    def __exit__(self, *exc) -> None:
        self._cur.close()

    def execute(self, sql: str, params=()) -> None:
        _perturb("pg.execute")
        with _EXEC_LOCK:
            try:
                self._cur.execute(_qmark(sql), tuple(params or ()))
            except sqlite3.Error as e:
                raise Error(str(e)) from e

    @property
    def description(self):
        return self._cur.description

    def fetchall(self):
        with _EXEC_LOCK:
            return self._cur.fetchall()


class FakeConnection:
    def __init__(self, dsn: str) -> None:
        with _ENGINES_LOCK:
            engine = _ENGINES.get(dsn)
            if engine is None:
                # check_same_thread=False: PgDb drives us via
                # asyncio.to_thread, and the default executor rotates threads.
                engine = sqlite3.connect(":memory:", check_same_thread=False)
                _ENGINES[dsn] = engine
        self._engine = engine
        self.closed = False

    def cursor(self) -> FakeCursor:
        if self.closed:
            raise Error("connection is closed")
        return FakeCursor(self._engine)

    def commit(self) -> None:
        _perturb("pg.commit")
        with _EXEC_LOCK:
            self._engine.commit()

    def rollback(self) -> None:
        with _EXEC_LOCK:
            self._engine.rollback()

    def close(self) -> None:
        # Keep the shared engine alive for other connections to the DSN.
        self.closed = True


def connect(dsn: str) -> FakeConnection:
    _perturb("pg.connect")
    return FakeConnection(dsn)


def reset() -> None:
    """Drop all fake databases (test isolation)."""
    global _FAULTS
    _FAULTS = None
    with _ENGINES_LOCK:
        for engine in _ENGINES.values():
            engine.close()
        _ENGINES.clear()


def install() -> None:
    """Register this module as ``psycopg`` so ``PgDb`` discovers it.

    Overwrites any previously-imported real driver: the caller only
    installs the fake when it wants the fake (e.g. a real psycopg exists
    but no server DSN is configured — resolving the real driver would dial
    the bogus fake DSN and error instead of running the fake)."""
    sys.modules["psycopg"] = sys.modules[__name__]
