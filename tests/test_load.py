"""Load telemetry, admission control, and affinity-priced placement.

Covers the rio_tpu.load subsystem end to end: vector codec + chaos
sanitization, the heartbeat-piggybacked ClusterLoadView, capacity derating
in the solver, per-object move pricing (hot/heavy actors priced differently
from cold ones), and the ServerBusy shed/retry loop over real sockets.
"""

import asyncio
import math
import time
import types

import numpy as np

from rio_tpu import (
    AppData,
    ClusterLoadView,
    LoadMonitor,
    LoadThresholds,
    LoadVector,
    ObjectId,
    ObjectPlacementItem,
    Registry,
    ServerInfo,
    ServiceObject,
    handler,
    message,
)
from rio_tpu.cluster.storage import Member
from rio_tpu.load import DEFAULT_MAX_STALENESS, MIN_DERATE, capacity_derate
from rio_tpu.object_placement.jax_placement import JaxObjectPlacement

from .server_utils import Cluster, run_integration_test


# ---------------------------------------------------------------------------
# LoadVector codec
# ---------------------------------------------------------------------------


def test_load_vector_roundtrip():
    v = LoadVector(
        loop_lag_ms=3.5, inflight=2, registry_objects=10,
        req_rate=1.25, state_bytes=4096, epoch=1700000000.0,
    )
    enc = v.encode()
    assert "," in enc and ";" not in enc  # must survive the Redis ';' join
    d = LoadVector.decode(enc)
    assert d is not None
    assert (d.loop_lag_ms, d.inflight, d.req_rate) == (3.5, 2, 1.25)


def test_load_vector_decode_tolerates_garbage():
    for raw in (None, "", "legacy", "1,2,3", "a,b,c,d,e,f"):
        assert LoadVector.decode(raw) is None
    # Parseable but insane values decode, then sanitize to something safe.
    v = LoadVector.decode("nan,-5,1e99,inf,-1,0")
    assert v is not None
    s = v.sanitized()
    assert s.loop_lag_ms == 0.0  # NaN -> default
    assert s.inflight == 0.0  # negative -> clamped
    assert math.isfinite(s.registry_objects)
    assert s.req_rate == 0.0  # inf -> default


def test_load_vector_sheds_append_only_growth():
    # Current 7-field rows round-trip the sheds counter...
    v = LoadVector(inflight=2, sheds=17.0, epoch=1700000000.0)
    d = LoadVector.decode(v.encode())
    assert d is not None and d.sheds == 17.0
    # ...pre-sheds 6-field legacy rows still decode (sheds defaults 0)...
    legacy = ",".join(v.encode().split(",")[:6])
    d6 = LoadVector.decode(legacy)
    assert d6 is not None and d6.sheds == 0.0 and d6.inflight == 2.0
    # ...and extra trailing fields from a NEWER sender are ignored.
    d8 = LoadVector.decode(v.encode() + ",99")
    assert d8 is not None and d8.sheds == 17.0


def test_capacity_derate_monotone_and_bounded():
    idle = capacity_derate(LoadVector())
    assert idle == 1.0
    assert capacity_derate(None) == 1.0
    hot = capacity_derate(LoadVector(loop_lag_ms=200.0, inflight=512))
    assert MIN_DERATE <= hot < idle
    # No input, however corrupt, escapes [MIN_DERATE, 1.0].
    for v in (
        LoadVector(loop_lag_ms=float("nan")),
        LoadVector(inflight=float("inf")),
        LoadVector(loop_lag_ms=-1e30, inflight=-5),
        LoadVector(loop_lag_ms=1e30, inflight=1e30),
    ):
        d = capacity_derate(v)
        assert MIN_DERATE <= d <= 1.0


# ---------------------------------------------------------------------------
# ClusterLoadView: staleness + chaos clamping
# ---------------------------------------------------------------------------


def _member(addr: str, load: str) -> Member:
    return Member.from_address(addr, active=True, load=load)


def test_cluster_view_staleness_and_garbage():
    now = time.time()
    fresh = LoadVector(inflight=512, epoch=now - 1.0).encode()
    old = LoadVector(inflight=512, epoch=now - 10 * DEFAULT_MAX_STALENESS).encode()
    zero_epoch = LoadVector(inflight=512, epoch=0.0).encode()
    future = LoadVector(inflight=512, epoch=now + 3600.0).encode()
    view = ClusterLoadView.from_members(
        [
            _member("10.0.0.1:1", fresh),
            _member("10.0.0.2:1", old),
            _member("10.0.0.3:1", zero_epoch),
            _member("10.0.0.4:1", future),
            _member("10.0.0.5:1", "total garbage"),
            _member("10.0.0.6:1", ""),  # legacy row: no vector at all
        ],
        now=now,
    )
    # Fresh + loaded: derated below 1.
    assert view.derate("10.0.0.1:1") < 1.0
    # Epoch-old: treated as unreported (derate 1.0), flagged stale.
    assert view.get("10.0.0.2:1").stale
    assert view.derate("10.0.0.2:1") == 1.0
    # Zero/future epochs are garbage -> infinitely stale, never "fresh".
    for addr in ("10.0.0.3:1", "10.0.0.4:1"):
        assert math.isinf(view.get(addr).staleness)
        assert view.derate(addr) == 1.0
    # Unparseable + legacy rows simply have no entry; unknown -> 1.0.
    assert view.get("10.0.0.5:1") is None
    assert view.derate("10.0.0.6:1") == 1.0
    assert view.derate("10.9.9.9:1") == 1.0
    # Gauges are flat floats; infinite staleness exports as -1.
    g = view.gauges()
    assert g["rio.cluster_load.10.0.0.1:1.inflight"] == 512.0
    assert g["rio.cluster_load.10.0.0.3:1.staleness"] == -1.0
    assert all(isinstance(x, float) and not math.isnan(x) for x in g.values())


def test_cluster_aggregate_gauges_roll_up_fresh_entries_only():
    now = time.time()
    a = LoadVector(loop_lag_ms=2.0, inflight=10, req_rate=100.0,
                   registry_objects=5, sheds=3.0, epoch=now - 1.0).encode()
    b = LoadVector(loop_lag_ms=6.0, inflight=30, req_rate=300.0,
                   registry_objects=15, sheds=4.0, epoch=now - 2.0).encode()
    stale = LoadVector(loop_lag_ms=999.0, inflight=999, req_rate=9999.0,
                       epoch=now - 10 * DEFAULT_MAX_STALENESS).encode()
    view = ClusterLoadView.from_members(
        [_member("10.0.0.1:1", a), _member("10.0.0.2:1", b),
         _member("10.0.0.3:1", stale)],
        now=now,
    )
    g = view.aggregate_gauges()
    assert g["rio.cluster.nodes"] == 2.0
    assert g["rio.cluster.nodes_stale"] == 1.0
    # The stale node's insane vector is excluded from every rollup.
    assert g["rio.cluster.loop_lag_mean_ms"] == 4.0
    assert g["rio.cluster.loop_lag_max_ms"] == 6.0
    assert g["rio.cluster.inflight_total"] == 40.0
    assert g["rio.cluster.req_rate_total"] == 400.0
    assert g["rio.cluster.registry_objects_total"] == 20.0
    assert g["rio.cluster.sheds_total"] == 7.0
    # The rollups ride the ordinary gauge scrape (fnmatch-selectable).
    assert view.gauges()["rio.cluster.req_rate_total"] == 400.0


def test_cluster_aggregate_gauges_empty_view_is_all_zero():
    view = ClusterLoadView.from_members([], now=time.time())
    g = view.aggregate_gauges()
    assert g["rio.cluster.nodes"] == 0.0
    assert all(v == 0.0 for v in g.values())


def test_cluster_view_chaos_vectors_all_bounded():
    """A cluster full of adversarial heartbeat rows produces only bounded
    derates — nothing a peer publishes can poison the solve inputs."""
    now = time.time()
    rows = [
        f"nan,nan,nan,nan,nan,{now}",
        f"-1e30,-5,-1,-1,-1,{now}",
        f"1e300,1e300,1e300,1e300,1e300,{now}",
        f"inf,-inf,inf,-inf,inf,{now}",
        "0,0,0,0,0,-50",
    ]
    members = [_member(f"10.1.0.{i}:1", raw) for i, raw in enumerate(rows)]
    view = ClusterLoadView.from_members(members, now=now)
    for m in members:
        d = view.derate(m.address)
        assert MIN_DERATE <= d <= 1.0 and not math.isnan(d)


# ---------------------------------------------------------------------------
# LoadMonitor: thresholds + sampling loop
# ---------------------------------------------------------------------------


def test_monitor_default_thresholds_never_shed():
    m = LoadMonitor()
    m.inflight = 10_000
    m.stats.loop_lag_ms = 1e9
    assert m.shed_reason() is None


def test_monitor_shed_reasons():
    registry = types.SimpleNamespace(count_objects=lambda: 5)
    m = LoadMonitor(
        registry=registry,
        thresholds=LoadThresholds(
            max_loop_lag_ms=50.0, max_inflight=4, max_registry_objects=10
        ),
    )
    assert m.shed_reason() is None
    m.inflight = 5
    assert "inflight" in m.shed_reason()
    m.inflight = 0
    m.stats.loop_lag_ms = 80.0
    assert "lag" in m.shed_reason()
    m.stats.loop_lag_ms = 0.0
    registry.count_objects = lambda: 11
    assert "registry" in m.shed_reason()


def test_monitor_peer_garbage_cannot_trigger_shedding():
    """Shed decisions read only local measurements: a view full of insane
    peer vectors changes nothing."""
    m = LoadMonitor(thresholds=LoadThresholds(max_inflight=100))
    m.cluster_view = ClusterLoadView.from_members(
        [_member("10.0.0.9:1", "inf,inf,inf,inf,inf,1")], now=time.time()
    )
    assert m.shed_reason() is None


async def test_monitor_samples_and_pushes_view():
    pushed = []

    class FakePlacement:
        def sync_load(self, view):
            pushed.append(view)

    class FakeMembers:
        async def members(self):
            return [
                _member(
                    "10.0.0.1:1",
                    LoadVector(inflight=300, epoch=time.time()).encode(),
                )
            ]

    m = LoadMonitor(
        members_storage=FakeMembers(),
        placement=FakePlacement(),
        interval=0.01,
        view_interval=0.01,
    )
    m.request_started()
    m.request_started()
    m.request_finished()
    task = asyncio.ensure_future(m.run())
    try:
        deadline = asyncio.get_event_loop().time() + 5.0
        while asyncio.get_event_loop().time() < deadline:
            if m.stats.samples >= 3 and pushed:
                break
            await asyncio.sleep(0.02)
    finally:
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
    assert m.stats.samples >= 3
    assert m.stats.inflight == 1
    assert m.cluster_view is not None and len(m.cluster_view) == 1
    assert pushed and pushed[0].derate("10.0.0.1:1") < 1.0
    # The published snapshot round-trips through the heartbeat encoding.
    decoded = LoadVector.decode(m.encoded_snapshot())
    assert decoded is not None and decoded.inflight == 1.0


# ---------------------------------------------------------------------------
# Solver consumer: capacity derating + per-object move pricing
# ---------------------------------------------------------------------------


def _jax_provider(nodes=2, **kw):
    p = JaxObjectPlacement(node_axis_size=16, **kw)
    for i in range(nodes):
        p.register_node(f"10.0.0.{i}:5000")
    return p


def _view_for(loads: dict[str, LoadVector]) -> ClusterLoadView:
    now = time.time()
    members = []
    for addr, vec in loads.items():
        vec.epoch = now
        members.append(_member(addr, vec.encode()))
    return ClusterLoadView.from_members(members, now=now)


def test_sync_load_derates_and_quantizes_epoch():
    p = _jax_provider()
    a, b = "10.0.0.0:5000", "10.0.0.1:5000"
    epoch0 = p._epoch
    # inflight 1792 -> derate 1/(1+7) = 0.125 (a quantization grid point).
    p.sync_load(_view_for({a: LoadVector(inflight=1792), b: LoadVector()}))
    assert p._nodes[a].reported_derate == 0.125
    assert p._nodes[b].reported_derate == 1.0
    assert p._epoch == epoch0 + 1
    # A tiny wobble inside the same 1/8 bucket must NOT bump the epoch
    # (it would discard every in-flight solve once per monitor tick).
    p.sync_load(_view_for({a: LoadVector(inflight=1800), b: LoadVector()}))
    assert p._epoch == epoch0 + 1
    # view=None resets to full capacity (one more epoch bump).
    p.sync_load(None)
    assert p._nodes[a].reported_derate == 1.0
    assert p._epoch == epoch0 + 2


async def test_assign_batch_respects_derated_capacity():
    p = _jax_provider()
    a, b = "10.0.0.0:5000", "10.0.0.1:5000"
    p.sync_load(_view_for({a: LoadVector(inflight=1792), b: LoadVector()}))
    addrs = await p.assign_batch([ObjectId("T", str(i)) for i in range(160)])
    counts = {a: addrs.count(a), b: addrs.count(b)}
    # Capacity columns are 0.125 vs 1.0: the healthy node takes the bulk.
    assert counts[b] > counts[a] * 3, counts
    assert counts[a] > 0  # floor: the hot node never vanishes entirely


async def test_sync_load_chaos_view_cannot_poison_assignment():
    p = _jax_provider()
    now = time.time()
    members = [
        _member("10.0.0.0:5000", f"nan,inf,-1,nan,inf,{now}"),
        _member("10.0.0.1:5000", "1e300,-1e300,nan,inf,0,-5"),
    ]
    p.sync_load(ClusterLoadView.from_members(members, now=now))
    for slot in p._nodes.values():
        assert 0.1 <= slot.reported_derate <= 1.0
    addrs = await p.assign_batch([ObjectId("T", str(i)) for i in range(64)])
    assert set(addrs) <= {"10.0.0.0:5000", "10.0.0.1:5000"}


async def test_rebalance_affinity_pricing_keeps_hot_objects():
    """Acceptance: a hot/heavy actor is assigned differently under
    per-object pricing than under uniform move cost.

    16 objects all seated on node a; node b joins with 3x the capacity, so
    the quota repair forces 12 of 16 to move. Under uniform cost the
    evicted 12 are an arbitrary choice; with object_costs pricing the 4
    hot actors 16x dearer, the solver must evict only cold ones.
    """
    a, b = "10.0.0.0:5000", "10.0.0.1:5000"
    keys = [f"T.{i}" for i in range(16)]
    hot = {keys[3], keys[7], keys[11], keys[15]}

    def object_costs(ks):
        return np.asarray([16.0 if k in hot else 1.0 for k in ks], np.float32)

    async def seed(p):
        for k in keys:
            t, _, i = k.partition(".")
            await p.update(ObjectPlacementItem(ObjectId(t, i), a))

    priced = JaxObjectPlacement(
        node_axis_size=16, mode="sinkhorn", move_cost=0.5,
        object_costs=object_costs,
    )
    priced.register_node(a, capacity=1.0)
    priced.register_node(b, capacity=3.0)
    await seed(priced)
    moved = await priced.rebalance(mode="sinkhorn")
    assert moved == 12
    stayers = {
        k for k in keys
        if await priced.lookup(ObjectId(*k.split("."))) == a
    }
    assert stayers == hot  # every survivor on a is a hot actor
    # Non-uniform prices must route the dense pipeline, not the collapse.
    assert priced.stats.mode == "sinkhorn"

    uniform = JaxObjectPlacement(
        node_axis_size=16, mode="sinkhorn", move_cost=0.5,
    )
    uniform.register_node(a, capacity=1.0)
    uniform.register_node(b, capacity=3.0)
    await seed(uniform)
    moved_u = await uniform.rebalance(mode="sinkhorn")
    assert moved_u == 12
    # Uniform pricing keeps the collapsed O(M^2) fast path (solver parity).
    assert uniform.stats.mode == "sinkhorn+collapsed"


async def test_rebalance_uniform_object_costs_keep_fast_path():
    """A hook returning all-equal weights is semantically the scalar
    move_cost: the collapsed fast path must survive it."""
    p = JaxObjectPlacement(
        node_axis_size=16, mode="sinkhorn", move_cost=0.5,
        object_costs=lambda ks: np.ones((len(ks),), np.float32),
    )
    p.register_node("10.0.0.0:5000")
    p.register_node("10.0.0.1:5000")
    for i in range(8):
        await p.update(ObjectPlacementItem(ObjectId("T", str(i)), "10.0.0.0:5000"))
    await p.rebalance(mode="sinkhorn")
    assert p.stats.mode == "sinkhorn+collapsed"


async def test_rebalance_broken_object_costs_degrade_to_uniform():
    """A hook that raises (or returns the wrong shape) must never break a
    rebalance — pricing degrades to uniform."""
    calls = {"n": 0}

    def broken(ks):
        calls["n"] += 1
        raise RuntimeError("telemetry offline")

    p = JaxObjectPlacement(
        node_axis_size=16, mode="sinkhorn", move_cost=0.5, object_costs=broken,
    )
    p.register_node("10.0.0.0:5000")
    p.register_node("10.0.0.1:5000")
    for i in range(8):
        await p.update(ObjectPlacementItem(ObjectId("T", str(i)), "10.0.0.0:5000"))
    moved = await p.rebalance(mode="sinkhorn")
    assert calls["n"] == 1
    assert moved == 4
    assert p.stats.mode == "sinkhorn+collapsed"


# ---------------------------------------------------------------------------
# Overload integration: ServerBusy shed -> client backoff -> healthy node
# ---------------------------------------------------------------------------


@message(name="load.Ping")
class Ping:
    pass


@message(name="load.Pong")
class Pong:
    address: str = ""


class Echo(ServiceObject):
    @handler
    async def ping(self, msg: Ping, ctx: AppData) -> Pong:
        return Pong(address=ctx.get(ServerInfo).address)


def build_registry() -> Registry:
    return Registry().add_type(Echo)


def test_overloaded_server_sheds_and_client_completes_elsewhere():
    """Acceptance: a saturated server sheds with ServerBusy; the client
    backs off, avoids it, and every request completes on the healthy
    member."""

    async def body(cluster: Cluster):
        s0, s1 = cluster.servers
        # Saturate s0 after boot: with max_inflight=0 every fresh
        # activation there sheds (the in-flight request itself counts).
        s0.load_monitor.thresholds = LoadThresholds(max_inflight=0)
        client = cluster.client()
        try:
            outs = [
                await client.send(Echo, f"e{i}", Ping(), returns=Pong)
                for i in range(20)
            ]
        finally:
            client.close()
        # Every request completed, all on the healthy node.
        assert {o.address for o in outs} == {s1.local_address}
        # The busy node really shed (20 random 2-way picks: P(no hit on
        # s0) = 2^-20) and the client answered with busy retries.
        assert s0.load_monitor.stats.sheds > 0
        assert client.stats.busy_retries > 0
        assert s1.load_monitor.stats.sheds == 0
        # Shed ids were un-seated, not parked: directory rows point at s1.
        assert (
            await cluster.allocation_address("Echo", "e0") == s1.local_address
        )
        assert not s0.registry.has("Echo", "e0")

    asyncio.run(
        run_integration_test(body, registry_builder=build_registry, num_servers=2)
    )


def test_activated_objects_keep_serving_while_shedding():
    """Only would-be activations shed: an object already live on the busy
    node keeps answering (bouncing it would redirect-ping-pong)."""

    async def body(cluster: Cluster):
        s0, s1 = cluster.servers
        client = cluster.client()
        try:
            # Seat one object on s0 while healthy.
            out = None
            for i in range(40):
                out = await client.send(Echo, f"warm{i}", Ping(), returns=Pong)
                if out.address == s0.local_address:
                    warm_id = f"warm{i}"
                    break
            assert out is not None and out.address == s0.local_address
            s0.load_monitor.thresholds = LoadThresholds(max_inflight=0)
            out = await client.send(Echo, warm_id, Ping(), returns=Pong)
            assert out.address == s0.local_address  # still served locally
        finally:
            client.close()

    asyncio.run(
        run_integration_test(body, registry_builder=build_registry, num_servers=2)
    )
