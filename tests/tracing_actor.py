"""Shared actor for the cross-process trace-propagation test.

Imported by BOTH sides of the real-socket run (the server child process
registers it; the parent test imports it for the client's codec). The
handler echoes the trace id the SERVER observed, so the parent can assert
the wire carried the client-rooted context across processes and hops.
"""

from rio_tpu import AppData, Registry, ServerInfo, ServiceObject, handler, message
from rio_tpu import tracing


@message(name="tr.Probe")
class Probe:
    pass


@message(name="tr.Seen")
class Seen:
    trace_id: str = ""
    address: str = ""


class TrEcho(ServiceObject):
    @handler
    async def probe(self, msg: Probe, ctx: AppData) -> Seen:
        return Seen(
            trace_id=tracing.current_trace_id() or "",
            address=ctx.get(ServerInfo).address,
        )


def build_registry() -> Registry:
    return Registry().add_type(TrEcho)
