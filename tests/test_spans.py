"""Span ring retention semantics: overflow accounting, tail capture,
tolerant wire rows, and the waterfall assembly over merged records."""

import time
from types import SimpleNamespace

from rio_tpu.spans import (
    PHASE_KEYS,
    Phases,
    SpanRecord,
    SpanRing,
    arm_client_ring,
    client_ring,
    disarm_client_ring,
    finish_request,
    merge_spans,
)


def _record(ring, i, trace_id="t", **attrs):
    return ring.record(
        trace_id=trace_id,
        span_id=f"s{i}",
        parent_id="",
        name="request",
        wall_start=1000.0 + i,
        duration_us=10 * i,
        attrs=attrs,
    )


def test_ring_overflow_dropped_accounting():
    """Overwrite-oldest with gap-free seqs: a full ring never blocks or
    fails, every overwritten record counts in ``dropped``, and snapshots
    return the surviving window oldest → newest."""
    ring = SpanRing(capacity=4, node="n1")
    for i in range(10):
        _record(ring, i)
    assert ring.retained == 10
    assert ring.dropped == 6
    assert len(ring) == 4
    assert [r.seq for r in ring.spans()] == [7, 8, 9, 10]
    # limit keeps the NEWEST matches (a tail, not a head).
    assert [r.seq for r in ring.spans(limit=2)] == [9, 10]
    # since_seq resumes a tail.
    assert [r.seq for r in ring.spans(since_seq=8)] == [9, 10]
    g = ring.gauges()
    assert g["rio.spans.retained"] == 10.0
    assert g["rio.spans.dropped"] == 6.0
    assert g["rio.spans.ring_occupancy"] == 4.0
    assert g["rio.spans.ring_capacity"] == 4.0


def test_ring_trace_filter():
    ring = SpanRing(capacity=8, node="n1")
    for i in range(6):
        _record(ring, i, trace_id="a" if i % 2 else "b")
    assert [r.seq for r in ring.spans(trace_id="a")] == [2, 4, 6]
    assert ring.spans(trace_id="nope") == []


def _env():
    return SimpleNamespace(
        handler_type="Svc", handler_id="g1", message_type="Get"
    )


def _phases(total_s: float, trace_ctx=None) -> Phases:
    t0 = 100.0
    ph = Phases(t0, trace_ctx)
    ph.decode = t0 + total_s * 0.1
    ph.queue = t0 + total_s * 0.2
    ph.handler_start = ph.queue
    ph.handler_end = t0 + total_s * 0.8
    ph.encode = t0 + total_s * 0.9
    ph.flush = t0 + total_s
    return ph


def test_tail_capture_over_slo():
    """Untraced requests are retained only past the SLO — with a fresh
    trace id, a ``tail=1`` attr, and the counter bumped; under the SLO
    nothing is recorded; traced requests always retain."""
    ring = SpanRing(capacity=8, node="n1", slo_ms=5.0)
    # 1 ms untraced: below the SLO, dropped on the floor.
    assert finish_request(ring, _phases(0.001), _env()) is None
    assert ring.retained == 0 and ring.tail_captured == 0
    # 10 ms untraced: tail-captured with a synthesized trace id.
    rec = finish_request(ring, _phases(0.010), _env())
    assert rec is not None
    assert ring.tail_captured == 1
    assert rec.attrs["tail"] == 1
    assert len(rec.trace_id) == 32 and rec.parent_id == ""
    assert rec.duration_us == 10_000
    # Fast but traced: the caller decided, always retained, no tail attr.
    rec2 = finish_request(ring, _phases(0.001, ("ab" * 16, "cd" * 8, True)), _env())
    assert rec2 is not None and ring.tail_captured == 1
    assert rec2.trace_id == "ab" * 16 and rec2.parent_id == "cd" * 8
    assert "tail" not in rec2.attrs
    # Phase decomposition covers the whole request, in pipeline order.
    for key in PHASE_KEYS:
        assert key in rec2.attrs and rec2.attrs[key] >= 0
    assert rec2.attrs["handler"] == "Svc/g1" and rec2.attrs["msg"] == "Get"
    assert sum(rec2.attrs[k] for k in PHASE_KEYS) <= rec2.duration_us


def test_tail_capture_disarmed_at_zero_slo():
    ring = SpanRing(capacity=8, node="n1", slo_ms=0.0)
    assert finish_request(ring, _phases(10.0), _env()) is None
    assert ring.retained == 0


def test_span_row_tolerant_decode():
    """Positional rows: short legacy rows pad with defaults, extra
    trailing fields from a newer sender are ignored (append-only growth)."""
    rec = SpanRecord(
        seq=3, trace_id="t", span_id="s", parent_id="p", name="request",
        node="n", wall_start=1234.5, duration_us=42, attrs={"handler": "S/x"},
    )
    row = rec.to_row()
    assert SpanRecord.from_row(row) == rec
    # A newer sender appended two fields: ignored, not an error.
    assert SpanRecord.from_row(row + ["future", 7]) == rec
    # A short legacy row decodes with defaults.
    legacy = SpanRecord.from_row([1, "t2", "s2"])
    assert legacy.seq == 1 and legacy.trace_id == "t2"
    assert legacy.node == "" and legacy.duration_us == 0 and legacy.attrs == {}


def test_merge_spans_orders_across_nodes():
    a, b = SpanRing(capacity=4, node="a"), SpanRing(capacity=4, node="b")
    _record(a, 5)  # wall_start 1005
    _record(b, 3)  # wall_start 1003
    _record(a, 7)  # wall_start 1007
    merged = merge_spans([a.spans(), b.spans()])
    assert [(r.node, r.seq) for r in merged] == [("b", 1), ("a", 1), ("a", 2)]


def test_assemble_waterfall_tree_and_events():
    """Hops nest under their wire parent; parentless hops root; journal
    events carrying the trace id join their trace's tree."""
    from rio_tpu.admin import assemble_waterfall, format_waterfall
    from rio_tpu.journal import JournalEvent

    ring = SpanRing(capacity=8, node="srv")
    client = SpanRing(capacity=8, node="")
    client.record(
        trace_id="T", span_id="root", parent_id="", name="client_request",
        wall_start=1000.0, duration_us=900,
        attrs={"handler": "Svc/g1", "send_us": 100, "await_us": 800,
               "redirects": 1},
    )
    ring.record(
        trace_id="T", span_id="h1", parent_id="root", name="request",
        wall_start=1000.1, duration_us=200,
        attrs={"handler": "Svc/g1", "status": 1, "decode_us": 5},
    )
    ring.record(
        trace_id="T", span_id="h2", parent_id="root", name="request",
        wall_start=1000.2, duration_us=300,
        attrs={"handler": "Svc/g1", "decode_us": 4},
    )
    ev = JournalEvent(
        seq=1, wall_ts=1000.05, mono_ts=1.0, node="srv", epoch=0,
        kind="place_assign", key="Svc/g1", attrs={}, trace_id="T",
    )
    trees = assemble_waterfall(
        merge_spans([ring.spans(), client.spans()]), [ev]
    )
    assert set(trees) == {"T"}
    tree = trees["T"]
    assert tree["hops"] == 3
    assert len(tree["roots"]) == 1
    root = tree["roots"][0]
    assert root["record"].span_id == "root"
    # Children in wall order: the redirect hop first.
    assert [c["record"].span_id for c in root["children"]] == ["h1", "h2"]
    assert tree["events"] == [ev]
    text = format_waterfall("T", tree)
    assert "client_request" in text and "status=1" in text
    assert "place_assign" in text
    # A hop whose parent no ring retained becomes a root, not an orphan.
    lone = SpanRing(capacity=2, node="x")
    lone.record(
        trace_id="U", span_id="u1", parent_id="gone", name="request",
        wall_start=1.0, duration_us=1, attrs={},
    )
    u = assemble_waterfall(lone.spans())["U"]
    assert len(u["roots"]) == 1 and u["roots"][0]["record"].span_id == "u1"


def test_client_ring_arm_disarm():
    assert client_ring() is None
    try:
        ring = arm_client_ring(capacity=16, slo_ms=1.5)
        assert client_ring() is ring
        assert ring.capacity == 16 and ring.slo_ms == 1.5 and ring.node == ""
    finally:
        disarm_client_ring()
    assert client_ring() is None


def test_phases_defaults_to_recv():
    t0 = time.monotonic()
    ph = Phases(t0)
    assert ph.decode == ph.queue == ph.handler_end == ph.flush == t0
    assert ph.trace_id == "" and ph.parent_id == "" and ph.attrs is None
    ph2 = Phases(t0, ("tid", "sid", True))
    assert ph2.trace_id == "tid" and ph2.parent_id == "sid"
