"""Child program for the REAL two-process multi-controller test.

Each process runs this same program (the SPMD contract): pin 2 local CPU
devices, join the cluster via rio_tpu.parallel.multihost.initialize, build
the global 4-device mesh, feed ONLY this process's object rows, solve, and
gather the global assignment. Process 0 writes the artifacts the parent
test asserts on.

Run by tests/test_multihost.py with a clean PYTHONPATH (the ambient axon
sitecustomize must not leak in — it re-registers the TPU plugin and the
solve would hang against a wedged relay).
"""

import os
import sys

pid, nproc, port, outdir = (
    int(sys.argv[1]),
    int(sys.argv[2]),
    sys.argv[3],
    sys.argv[4],
)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

from rio_tpu.parallel import make_mesh, multihost  # noqa: E402

ok = multihost.initialize(
    f"127.0.0.1:{port}", num_processes=nproc, process_id=pid
)
assert ok and multihost.is_multihost(), (ok, jax.process_count())
assert jax.device_count() == 2 * nproc

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.experimental import multihost_utils  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from rio_tpu.parallel.hierarchical import sharded_hierarchical_assign  # noqa: E402

N_OBJ, D, M, G = 256, 8, 16, 4
DEAD = 3

mesh = make_mesh()  # spans every process's devices
key = jax.random.PRNGKey(3)
k1, k2 = jax.random.split(key)
# Deterministic global inputs: every process derives identical arrays and
# feeds only its own rows.
obj_all = np.asarray(jax.random.normal(k1, (N_OBJ, D), jnp.float32))
node_feat = np.asarray(jax.random.normal(k2, (D, M), jnp.float32)) * 0.2
rows = multihost.process_rows(N_OBJ, mesh)
axes = tuple(mesh.axis_names)
obj_feat = multihost.distributed_array(mesh, P(axes, None), obj_all[rows])
cap = jnp.ones((M,), jnp.float32)
alive = jnp.ones((M,), jnp.float32).at[DEAD].set(0.0)
res = sharded_hierarchical_assign(
    mesh, obj_feat, node_feat, cap, alive,
    n_groups=G, coarse_iters=8, fine_iters=8,
)
assignment = multihost_utils.process_allgather(res.assignment, tiled=True)
if pid == 0:
    np.save(os.path.join(outdir, "assignment.npy"), np.asarray(assignment))
    np.save(
        os.path.join(outdir, "meta.npy"),
        np.asarray([int(res.overflow), mesh.shape["obj"] * mesh.shape["node"]]),
    )
print(f"[{pid}] done", flush=True)
