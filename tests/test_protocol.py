"""Unit tests for wire envelopes (rio_tpu.protocol)."""

import pytest

from rio_tpu import protocol
from rio_tpu.errors import SerializationError
from rio_tpu.protocol import (
    ErrorKind,
    RequestEnvelope,
    ResponseEnvelope,
    ResponseError,
    SubscriptionRequest,
    SubscriptionResponse,
)


def test_request_envelope_roundtrip():
    env = RequestEnvelope("Svc", "obj-1", "Ping", b"\x01\x02")
    assert RequestEnvelope.from_bytes(env.to_bytes()) == env


def test_response_ok_roundtrip():
    env = ResponseEnvelope.ok(b"result")
    out = ResponseEnvelope.from_bytes(env.to_bytes())
    assert out.is_ok and out.body == b"result"


@pytest.mark.parametrize(
    "err",
    [
        ResponseError.redirect("10.0.0.1:9000"),
        ResponseError.deallocate(),
        ResponseError.allocate("boom"),
        ResponseError.not_supported("NoSuchType"),
        ResponseError.application(b"payload", "MyError"),
        ResponseError.unknown("Panic: ..."),
    ],
)
def test_response_error_roundtrip(err):
    out = ResponseEnvelope.from_bytes(ResponseEnvelope.err(err).to_bytes())
    assert not out.is_ok
    assert out.error == err


def test_redirect_carries_address():
    out = ResponseEnvelope.from_bytes(
        ResponseEnvelope.err(ResponseError.redirect("1.2.3.4:5")).to_bytes()
    )
    assert out.error.kind == ErrorKind.REDIRECT
    assert out.error.detail == "1.2.3.4:5"


def test_subscription_roundtrips():
    req = SubscriptionRequest("Svc", "obj")
    assert SubscriptionRequest.from_bytes(req.to_bytes()) == req

    ok = SubscriptionResponse(body=b"data", message_type="Tick")
    out = SubscriptionResponse.from_bytes(ok.to_bytes())
    assert out.error is None and out.body == b"data" and out.message_type == "Tick"

    err = SubscriptionResponse(error=ResponseError.redirect("a:1"))
    out = SubscriptionResponse.from_bytes(err.to_bytes())
    assert out.error is not None and out.error.kind == ErrorKind.REDIRECT


def test_frame_kind_dispatch():
    req = RequestEnvelope("S", "i", "M", b"")
    decoded = protocol.decode_inbound(protocol.KIND_REQUEST + req.to_bytes())
    assert isinstance(decoded, RequestEnvelope)

    sub = SubscriptionRequest("S", "i")
    decoded = protocol.decode_inbound(protocol.KIND_SUBSCRIBE + sub.to_bytes())
    assert isinstance(decoded, SubscriptionRequest)

    with pytest.raises(SerializationError):
        protocol.decode_inbound(b"\x07junk")
    with pytest.raises(SerializationError):
        protocol.decode_inbound(b"")
