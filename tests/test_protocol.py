"""Unit tests for wire envelopes (rio_tpu.protocol)."""

import pytest

from rio_tpu import protocol
from rio_tpu.errors import SerializationError
from rio_tpu.protocol import (
    ErrorKind,
    RequestEnvelope,
    ResponseEnvelope,
    ResponseError,
    SubscriptionRequest,
    SubscriptionResponse,
)


def test_request_envelope_roundtrip():
    env = RequestEnvelope("Svc", "obj-1", "Ping", b"\x01\x02")
    assert RequestEnvelope.from_bytes(env.to_bytes()) == env


def test_response_ok_roundtrip():
    env = ResponseEnvelope.ok(b"result")
    out = ResponseEnvelope.from_bytes(env.to_bytes())
    assert out.is_ok and out.body == b"result"


@pytest.mark.parametrize(
    "err",
    [
        ResponseError.redirect("10.0.0.1:9000"),
        ResponseError.deallocate(),
        ResponseError.allocate("boom"),
        ResponseError.not_supported("NoSuchType"),
        ResponseError.application(b"payload", "MyError"),
        ResponseError.unknown("Panic: ..."),
    ],
)
def test_response_error_roundtrip(err):
    out = ResponseEnvelope.from_bytes(ResponseEnvelope.err(err).to_bytes())
    assert not out.is_ok
    assert out.error == err


def test_redirect_carries_address():
    out = ResponseEnvelope.from_bytes(
        ResponseEnvelope.err(ResponseError.redirect("1.2.3.4:5")).to_bytes()
    )
    assert out.error.kind == ErrorKind.REDIRECT
    assert out.error.detail == "1.2.3.4:5"


def test_subscription_roundtrips():
    req = SubscriptionRequest("Svc", "obj")
    assert SubscriptionRequest.from_bytes(req.to_bytes()) == req

    ok = SubscriptionResponse(body=b"data", message_type="Tick")
    out = SubscriptionResponse.from_bytes(ok.to_bytes())
    assert out.error is None and out.body == b"data" and out.message_type == "Tick"

    err = SubscriptionResponse(error=ResponseError.redirect("a:1"))
    out = SubscriptionResponse.from_bytes(err.to_bytes())
    assert out.error is not None and out.error.kind == ErrorKind.REDIRECT


def test_request_envelope_trace_ctx_roundtrip():
    ctx = ("ab" * 16, "cd" * 8, True)
    env = RequestEnvelope("Svc", "obj-1", "Ping", b"\x01\x02", ctx)
    out = RequestEnvelope.from_bytes(env.to_bytes())
    assert out == env
    assert out.trace_ctx == ctx


def test_untraced_frame_is_byte_identical_to_legacy():
    """Appended-field contract, old-decoder direction: an untraced envelope
    encodes EXACTLY the pre-trace 4-element wire, so a peer that predates
    trace_ctx parses it unchanged. Pinned against hand-built legacy bytes,
    not a round-trip (a symmetric bug would pass a round-trip)."""
    from rio_tpu import codec

    env = RequestEnvelope("Svc", "obj-1", "Ping", b"\x01\x02")
    legacy = codec.serialize(["Svc", "obj-1", "Ping", b"\x01\x02"])
    assert env.to_bytes() == legacy


def test_new_decoder_accepts_legacy_frame():
    """Old-encoder direction: a 4-element frame from a pre-trace peer
    decodes with trace_ctx defaulting to None."""
    from rio_tpu import codec

    legacy = codec.serialize(["Svc", "obj-1", "Ping", b"\x01\x02"])
    out = RequestEnvelope.from_bytes(legacy)
    assert out == RequestEnvelope("Svc", "obj-1", "Ping", b"\x01\x02")
    assert out.trace_ctx is None


def test_traced_frame_kind_dispatch():
    ctx = ("f" * 32, "0" * 16, True)
    env = RequestEnvelope("S", "i", "M", b"pp", ctx)
    decoded = protocol.decode_inbound(protocol.KIND_REQUEST + env.to_bytes())
    assert isinstance(decoded, RequestEnvelope)
    assert decoded.trace_ctx == ctx


def test_frame_kind_dispatch():
    req = RequestEnvelope("S", "i", "M", b"")
    decoded = protocol.decode_inbound(protocol.KIND_REQUEST + req.to_bytes())
    assert isinstance(decoded, RequestEnvelope)

    sub = SubscriptionRequest("S", "i")
    decoded = protocol.decode_inbound(protocol.KIND_SUBSCRIBE + sub.to_bytes())
    assert isinstance(decoded, SubscriptionRequest)

    with pytest.raises(SerializationError):
        protocol.decode_inbound(b"\x07junk")
    with pytest.raises(SerializationError):
        protocol.decode_inbound(b"")
