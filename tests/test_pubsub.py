"""Pub/sub integration tests.

Reference: ``rio-rs/tests/client_server_integration_test.rs:182-307`` —
subscribe to an object's stream, receive handler-published messages,
redirect-following resubscribe.
"""

import asyncio

from rio_tpu import AppData, MessageRouter, Registry, ServiceObject, handler, message
from rio_tpu.registry import type_id

from .server_utils import Cluster, run_integration_test


@message
class Publish:
    text: str = ""


@message
class Done:
    pass


@message
class Event:
    text: str = ""
    seq: int = 0


class Broadcaster(ServiceObject):
    def __init__(self):
        self.seq = 0

    @handler
    async def publish(self, msg: Publish, ctx: AppData) -> Done:
        self.seq += 1
        router = ctx.get(MessageRouter)
        router.publish(type_id(Broadcaster), self.id, Event(text=msg.text, seq=self.seq))
        return Done()


def build_registry() -> Registry:
    return Registry().add_type(Broadcaster)


def test_subscribe_receives_published_messages():
    async def body(cluster: Cluster):
        client = cluster.client()
        # Allocate the object first so the subscription lands on its host.
        await client.send(Broadcaster, "b1", Publish(text="warmup"), returns=Done)

        stream = await client.subscribe(Broadcaster, "b1")
        received: list[Event] = []

        async def consume():
            async for event in stream:
                received.append(event)
                if len(received) == 3:
                    return

        consumer = asyncio.create_task(consume())
        await asyncio.sleep(0.2)  # let the subscription attach
        for i in range(3):
            await client.send(Broadcaster, "b1", Publish(text=f"m{i}"), returns=Done)
        await asyncio.wait_for(consumer, timeout=5)

        assert [e.text for e in received] == ["m0", "m1", "m2"]
        assert [e.seq for e in received] == [2, 3, 4]  # warmup was seq 1
        assert all(isinstance(e, Event) for e in received)
        client.close()

    asyncio.run(run_integration_test(body, registry_builder=build_registry, num_servers=2))


def test_subscribe_from_cold_cache_follows_redirect():
    async def body(cluster: Cluster):
        c1 = cluster.client()
        await c1.send(Broadcaster, "b2", Publish(text="seed"), returns=Done)

        # Fresh client: random first pick, must end up streaming from the
        # true owner via redirect-following resubscribe.
        c2 = cluster.client()
        stream = await c2.subscribe(Broadcaster, "b2")
        received = []

        async def consume():
            async for event in stream:
                received.append(event)
                return

        consumer = asyncio.create_task(consume())
        await asyncio.sleep(0.3)
        await c1.send(Broadcaster, "b2", Publish(text="hello"), returns=Done)
        await asyncio.wait_for(consumer, timeout=5)
        assert received[0].text == "hello"
        c1.close()
        c2.close()

    asyncio.run(
        run_integration_test(body, registry_builder=build_registry, num_servers=6)
    )


def test_subscriber_follows_migrated_publisher():
    """A live migration of the publisher terminates its streams with a
    Redirect; the client's subscribe loop resubscribes at the new owner and
    keeps receiving events published after the move."""

    async def body(cluster: Cluster):
        from rio_tpu import AdminCommand

        client = cluster.client()
        await client.send(Broadcaster, "b4", Publish(text="seed"), returns=Done)
        source_addr = await cluster.allocation_address("Broadcaster", "b4")
        source = next(s for s in cluster.servers if s.local_address == source_addr)
        target = next(s for s in cluster.servers if s.local_address != source_addr)

        stream = await client.subscribe(Broadcaster, "b4")
        received: list[str] = []

        async def consume():
            async for event in stream:
                received.append(event.text)
                if "after-move" in received:
                    return

        consumer = asyncio.create_task(consume())
        await asyncio.sleep(0.3)  # let the subscription attach on the source
        await client.send(Broadcaster, "b4", Publish(text="before-move"), returns=Done)

        source.admin_sender().send(
            AdminCommand.migrate("Broadcaster", "b4", target.local_address)
        )
        deadline = asyncio.get_event_loop().time() + 10.0
        while asyncio.get_event_loop().time() < deadline:
            if source.migration_manager.stats.completed:
                break
            await asyncio.sleep(0.02)
        assert source.migration_manager.stats.completed == 1

        # Publish at the NEW owner until the resubscribed stream delivers:
        # the redirect item and the reconnect race, so one publish may land
        # between streams and is legitimately unreceived.
        deadline = asyncio.get_event_loop().time() + 10.0
        while asyncio.get_event_loop().time() < deadline and not consumer.done():
            await client.send(
                Broadcaster, "b4", Publish(text="after-move"), returns=Done
            )
            await asyncio.sleep(0.1)
        await asyncio.wait_for(consumer, timeout=5)

        assert "before-move" in received
        assert "after-move" in received
        assert (
            await cluster.allocation_address("Broadcaster", "b4")
            == target.local_address
        )
        client.close()

    asyncio.run(run_integration_test(body, registry_builder=build_registry, num_servers=2))


def test_multiple_subscribers_fan_out():
    async def body(cluster: Cluster):
        client = cluster.client()
        await client.send(Broadcaster, "b3", Publish(text="seed"), returns=Done)

        streams = [await cluster.client().subscribe(Broadcaster, "b3") for _ in range(3)]
        results: list[list[str]] = [[] for _ in streams]

        async def consume(i, stream):
            async for event in stream:
                results[i].append(event.text)
                if len(results[i]) == 2:
                    return

        consumers = [asyncio.create_task(consume(i, s)) for i, s in enumerate(streams)]
        await asyncio.sleep(0.3)
        await client.send(Broadcaster, "b3", Publish(text="x"), returns=Done)
        await client.send(Broadcaster, "b3", Publish(text="y"), returns=Done)
        await asyncio.wait_for(asyncio.gather(*consumers), timeout=5)
        assert results == [["x", "y"]] * 3
        client.close()

    asyncio.run(run_integration_test(body, registry_builder=build_registry, num_servers=2))


def test_router_channels_do_not_leak():
    """Regression: ``_channels`` grew without bound — ``publish`` to an
    object with no subscribers materialized a permanent ``_Broadcast``
    (fire-and-forget publishers), and the last ``drop_subscription`` left
    the empty channel behind. Both paths must leave the map empty."""
    router = MessageRouter()

    # Publish-only path: no subscriber ever existed -> no channel created.
    for i in range(1000):
        assert router.publish(type_id(Broadcaster), f"ghost-{i}", Event(seq=i)) == 0
    assert len(router._channels) == 0

    # Subscribe/unsubscribe path: the last drop prunes the channel.
    q1 = router.create_subscription("T", "a")
    q2 = router.create_subscription("T", "a")
    assert len(router._channels) == 1
    assert router.publish("T", "a", Event(seq=1)) == 2
    router.drop_subscription("T", "a", q1)
    assert len(router._channels) == 1  # one live subscriber keeps it
    assert router.publish("T", "a", Event(seq=2)) == 1
    router.drop_subscription("T", "a", q2)
    assert len(router._channels) == 0
    # Dropping again (or on an unknown key) stays a no-op.
    router.drop_subscription("T", "a", q2)
    assert len(router._channels) == 0

    # close_subscriptions pops too (migration handoff path).
    q3 = router.create_subscription("T", "b")
    assert router.close_subscriptions("T", "b", error=None) == 1
    assert len(router._channels) == 0
    assert q3.qsize() == 1  # the final error item was delivered


def test_router_overflow_is_observable():
    """Regression: a full subscriber queue silently displaced the oldest
    item — ``publish`` still counted the laggard as a receiver, so a
    durable-stream fan-in lost messages with no trace anywhere. Overflow
    stays survivable (broadcast-lag semantics) but must surface through
    the ``rio.router.dropped`` gauge."""
    router = MessageRouter(capacity=2)
    q = router.create_subscription("T", "a")
    fast = router.create_subscription("T", "a")

    for seq in range(2):
        assert router.publish("T", "a", Event(seq=seq)) == 2
    assert router.dropped == 0

    # Drain only the fast subscriber; the laggard's queue is now full.
    while not fast.empty():
        fast.get_nowait()
    assert router.publish("T", "a", Event(seq=2)) == 2  # still "delivered"
    assert router.dropped == 1  # ...but the displacement is visible
    assert router.publish("T", "a", Event(seq=3)) == 2
    assert router.dropped == 2
    assert fast.qsize() == 2  # the healthy subscriber lost nothing

    # Oldest-first displacement: the laggard kept the newest two.
    import rio_tpu.codec as _codec
    kept = [
        _codec.deserialize(q.get_nowait().body, Event).seq for _ in range(2)
    ]
    assert kept == [2, 3]

    # The gauge rides the standard surface the collector scrapes.
    assert router.gauges()["rio.router.dropped"] == 2.0
