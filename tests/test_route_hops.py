"""Measured route hops on a live cluster (BASELINE.md acceptance row).

Reference shape: ``rio-rs/tests/client_server_integration_test.rs:153-180``
(many objects spread over servers, client follows real Redirects). The
acceptance criterion under test is BASELINE.md's "≥20% lower p99 route
hops vs the SQL/random policy" — measured over real TCP round trips, not
the numpy simulation.
"""

import pytest

from rio_tpu.utils.routing_live import measure_route_hops_live


@pytest.mark.asyncio
async def test_directory_policy_beats_random_policy_p99():
    stats = await measure_route_hops_live(n_servers=8, n_objects=256)
    ref, ours = stats["reference"], stats["rio_tpu"]
    # Every request completed in at least one hop.
    assert ours.n_requests == ref.n_requests == 256
    assert ours.p50 >= 1.0 and ref.p50 >= 1.0
    # Directory-resolved dials go straight to the owner: p99 of 1 hop.
    # Random picks redirect with probability (n_servers-1)/n_servers, so
    # p99 is 2 hops. Acceptance: >=20% lower p99 (BASELINE.md row "route
    # hops"), and a strictly lower mean.
    assert ours.p99 <= 0.8 * ref.p99, (ours, ref)
    assert ours.mean < ref.mean, (ours, ref)


@pytest.mark.asyncio
async def test_directory_policy_hops_are_exactly_one():
    stats = await measure_route_hops_live(n_servers=4, n_objects=64)
    ours = stats["rio_tpu"]
    # With a fresh directory and no churn, every directory dial is exact.
    assert ours.mean == 1.0 and ours.p99 == 1.0, ours


def test_stale_directory_degrades_gracefully():
    """A poisoned directory snapshot costs bounded hops, never failures.

    16 servers, 4 of them killed after allocation; the stale resolver still
    points displaced objects at dead addresses and 8% of the rest at wrong
    live nodes. Every request must still succeed (redirect-follow +
    dial-failure fallback), and the fresh-directory policy stays at 1 hop.
    """
    import asyncio as _asyncio

    from rio_tpu.utils.routing_live import measure_route_hops_scaled

    out = _asyncio.run(
        measure_route_hops_scaled(n_servers=16, n_objects=2000, sample_size=800)
    )
    assert out["stale_failures"] == 0
    assert out["directory"]["mean"] == 1.0
    assert out["stale"]["p99"] <= 4  # dead dial + fallback + possible redirect
    assert out["reference"]["mean"] > out["directory"]["mean"]
    assert out["displaced"] > 0 and out["wrong"] > 0
