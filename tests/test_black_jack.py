"""Black-jack game-rule unit tests (reference
``examples/black-jack/tests/game.rs``): pure rules, no framework."""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "examples"))

from black_jack import (  # noqa: E402
    Deck,
    GameEngine,
    dealer_should_hit,
    hand_value,
    is_blackjack,
    settle,
)


def test_hand_values():
    assert hand_value(["2♠", "3♥"]) == 5
    assert hand_value(["K♠", "Q♥"]) == 20
    assert hand_value(["A♠", "K♥"]) == 21            # blackjack
    assert hand_value(["A♠", "A♥"]) == 12            # one ace demotes
    assert hand_value(["A♠", "A♥", "A♦", "A♣"]) == 14
    assert hand_value(["A♠", "9♥", "5♦"]) == 15      # soft 15 -> hard
    assert hand_value(["K♠", "Q♥", "5♦"]) == 25      # bust stays bust


def test_blackjack_detection():
    assert is_blackjack(["A♠", "J♥"])
    assert not is_blackjack(["A♠", "5♥", "5♦"])      # 21 in 3 cards ≠ blackjack
    assert not is_blackjack(["10♠", "9♥"])


def test_dealer_policy_draws_to_17():
    assert dealer_should_hit(["K♠", "6♥"])           # 16: hit
    assert not dealer_should_hit(["K♠", "7♥"])       # 17: stand
    assert not dealer_should_hit(["A♠", "6♥"])       # soft 17: stand (all 17s)


def test_settle_outcomes():
    assert settle(["K♠", "Q♥", "5♦"], ["K♥", "7♦"]) == "player_bust"
    assert settle(["A♠", "K♥"], ["K♦", "9♣"]) == "player_blackjack"
    assert settle(["A♠", "K♥"], ["A♦", "K♣"]) == "push"  # BJ vs BJ
    assert settle(["A♠", "5♥", "5♦"], ["A♦", "K♣"]) == "dealer_win"  # natural beats made 21
    assert settle(["A♠", "K♥"], ["A♦", "5♣", "5♥"]) == "player_blackjack"
    assert settle(["10♠", "9♥"], ["K♦", "6♣", "9♠"]) == "dealer_bust"
    assert settle(["10♠", "9♥"], ["K♦", "8♣"]) == "player_win"
    assert settle(["10♠", "7♥"], ["K♦", "8♣"]) == "dealer_win"
    assert settle(["10♠", "8♥"], ["K♦", "8♣"]) == "push"


def test_deck_is_seeded_and_complete():
    d1, d2 = Deck(seed=42), Deck(seed=42)
    assert d1.cards == d2.cards
    assert len(set(d1.cards)) == 52
    assert Deck(seed=1).cards != Deck(seed=2).cards


def test_engine_full_round():
    eng = GameEngine("t1", seed=7)
    s = eng.apply("join", "ada")
    assert s.phase in ("player_turn", "settled")
    if s.phase == "player_turn":
        s = eng.apply("stand")
    assert s.phase == "settled"
    assert s.outcome in (
        "player_win", "dealer_win", "push",
        "player_blackjack", "player_bust", "dealer_bust",
    )
    # dealer finished by policy
    assert not dealer_should_hit(s.dealer_cards) or s.outcome == "player_bust"


def test_engine_player_bust():
    eng = GameEngine("t2", seed=3)
    s = eng.apply("join", "bob")
    while s.phase == "player_turn":
        s = eng.apply("hit")
    assert s.phase == "settled"
    if hand_value(s.player_cards) > 21:
        assert s.outcome == "player_bust"


def test_engine_rejects_out_of_phase_commands():
    eng = GameEngine("t3", seed=5)
    with pytest.raises(ValueError):
        eng.apply("hit")                # can't hit before joining
    s = eng.apply("join", "cy")
    if s.phase == "settled":            # dealt blackjack: no more moves
        with pytest.raises(ValueError):
            eng.apply("stand")
    else:
        eng.apply("stand")
        with pytest.raises(ValueError):
            eng.apply("hit")            # settled: no more hits


def test_dealer_hidden_card_until_settled():
    eng = GameEngine("t4", seed=11)
    s = eng.apply("join", "dee")
    if s.phase == "player_turn":
        assert s.visible_dealer()[1] == "??"
        s = eng.apply("stand")
    assert "??" not in s.visible_dealer()
