"""C++ data plane tests: codec wire parity + epoll transport integration.

The native library must be byte-identical to the Python codec on every
envelope type (the two are interchangeable on the wire), and a server
running on the native epoll transport must pass the same integration
shapes as the asyncio transport (request/response, typed errors,
redirects, pub/sub)."""

import asyncio

import pytest

from rio_tpu import AppData, Registry, ServiceObject, handler, message, wire_error
from rio_tpu import codec, native, protocol
from rio_tpu.message_router import MessageRouter

from .server_utils import Cluster, run_integration_test

lib = native.get()
pytestmark = pytest.mark.skipif(lib is None, reason="native library unavailable")


# ---------------------------------------------------------------------------
# Codec parity
# ---------------------------------------------------------------------------


def test_request_frame_parity():
    for ht, hid, mt, payload in [
        ("Svc", "obj-1", "Msg", b"\x01\x02payload"),
        ("", "", "", b""),
        ("x" * 40, "y" * 300, "z" * 70000, b"p" * 70000),
    ]:
        env = protocol.RequestEnvelope(ht, hid, mt, payload)
        assert protocol.encode_request_frame(env) == lib.encode_request_frame(
            ht.encode(), hid.encode(), mt.encode(), payload
        )
        # Python reference path must produce the same bytes.
        assert codec.frame(protocol.KIND_REQUEST + env.to_bytes()) == (
            lib.encode_request_frame(ht.encode(), hid.encode(), mt.encode(), payload)
        )


def test_traced_request_frame_parity():
    """The appended trace_ctx keeps byte parity in BOTH arities: untraced
    envelopes must match the legacy 4-element encoder (wire-append
    contract), traced ones the new 5-element entry point."""
    tid, sid = "a1" * 16, "b2" * 8
    for sampled in (True, False):
        env = protocol.RequestEnvelope("Svc", "obj-1", "Msg", b"pp", (tid, sid, sampled))
        assert protocol.encode_request_frame(env) == lib.encode_request_frame_traced(
            b"Svc", b"obj-1", b"Msg", b"pp", tid.encode(), sid.encode(), sampled
        )
    # Untraced stays on the legacy entry point, byte-identical.
    env = protocol.RequestEnvelope("Svc", "obj-1", "Msg", b"pp")
    assert protocol.encode_request_frame(env) == lib.encode_request_frame(
        b"Svc", b"obj-1", b"Msg", b"pp"
    )


def test_traced_decode_inbound_parity():
    tid, sid = "c3" * 16, "d4" * 8
    env = protocol.RequestEnvelope("Svc", "i", "M", b"xyz", (tid, sid, True))
    framed = protocol.encode_request_frame(env)
    assert lib.decode_inbound(framed[4:]) == (
        0, b"Svc", b"i", b"M", b"xyz", tid.encode(), sid.encode(), True,
    )
    # Legacy (untraced) frames keep the historical 5-tuple shape.
    legacy = protocol.encode_request_frame(protocol.RequestEnvelope("S", "i", "M", b"x"))
    assert lib.decode_inbound(legacy[4:]) == (0, b"S", b"i", b"M", b"x")
    # Python typed decode agrees.
    back = protocol.decode_inbound(framed[4:])
    assert back == env and back.trace_ctx == (tid, sid, True)


def test_response_frame_parity():
    ok = protocol.ResponseEnvelope.ok(b"hello")
    assert codec.frame(ok.to_bytes()) == lib.encode_response_ok_frame(b"hello")
    err = protocol.ResponseEnvelope.err(
        protocol.ResponseError.application(b"errbytes", "MyErr")
    )
    assert codec.frame(err.to_bytes()) == lib.encode_response_err_frame(
        5, b"MyErr", b"errbytes"
    )
    # body=None normalizes to bin0 so both encoders emit identical bytes.
    none_body = protocol.ResponseEnvelope.ok(None)
    assert codec.frame(none_body.to_bytes()) == lib.encode_response_ok_frame(b"")
    assert protocol.ResponseEnvelope.from_bytes(none_body.to_bytes()).body == b""
    # SERVER_BUSY (kind 8): the overload-shed error rides the same arm —
    # the C++ side treats kind as an opaque uint, so parity must hold with
    # no native change.
    busy = protocol.ResponseEnvelope.err(
        protocol.ResponseError.server_busy("inflight>256")
    )
    assert codec.frame(busy.to_bytes()) == lib.encode_response_err_frame(
        int(protocol.ErrorKind.SERVER_BUSY), b"inflight>256", b""
    )
    # DEADLINE_EXCEEDED (kind 9): the QoS doomed-work shed rides the same
    # opaque-uint arm, again with no native change.
    late = protocol.ResponseEnvelope.err(
        protocol.ResponseError.deadline_exceeded("budget spent in queue")
    )
    assert codec.frame(late.to_bytes()) == lib.encode_response_err_frame(
        int(protocol.ErrorKind.DEADLINE_EXCEEDED), b"budget spent in queue", b""
    )
    # Decoders agree with the Python ones.
    assert lib.decode_response(ok.to_bytes()) == (True, b"hello")
    assert lib.decode_response(err.to_bytes()) == (False, 5, b"MyErr", b"errbytes")
    assert lib.decode_response(busy.to_bytes()) == (False, 8, b"inflight>256", b"")
    assert lib.decode_response(late.to_bytes()) == (False, 9, b"budget spent in queue", b"")
    assert lib.decode_response(b"\x00garbage") is None


def test_subscription_frame_parity():
    sub = protocol.SubscriptionRequest("Svc", "id9")
    assert protocol.encode_subscribe_frame(sub) == lib.encode_subscribe_frame(
        b"Svc", b"id9"
    )
    ok = protocol.SubscriptionResponse(body=b"bb", message_type="T")
    assert codec.frame(ok.to_bytes()) == lib.encode_subresponse_ok_frame(b"T", b"bb")
    assert lib.decode_subresponse(ok.to_bytes()) == (True, b"T", b"bb")
    err = protocol.SubscriptionResponse(
        error=protocol.ResponseError.redirect("1.2.3.4:5")
    )
    assert codec.frame(err.to_bytes()) == lib.encode_subresponse_err_frame(
        1, b"1.2.3.4:5", b""
    )
    assert lib.decode_subresponse(err.to_bytes()) == (False, 1, b"1.2.3.4:5", b"")


def test_decode_inbound_parity():
    env = protocol.RequestEnvelope("Svc", "i", "M", b"xyz")
    framed = protocol.encode_request_frame(env)
    assert lib.decode_inbound(framed[4:]) == (0, b"Svc", b"i", b"M", b"xyz")
    sub = protocol.SubscriptionRequest("Svc", "j")
    framed = protocol.encode_subscribe_frame(sub)
    assert lib.decode_inbound(framed[4:]) == (1, b"Svc", b"j")
    assert lib.decode_inbound(b"\x07nope") is None
    # protocol.decode_inbound (native fast path) returns the typed envelopes
    back = protocol.decode_inbound(protocol.encode_request_frame(env)[4:])
    assert back == env


def test_command_frame_parity():
    """KIND_COMMAND (streams/sagas control plane) byte parity, both
    arities, plus the rc=2 decode shape mirroring requests."""
    if not lib.has_command:
        pytest.skip("prebuilt native lib predates command frames")
    env = protocol.CommandEnvelope("stream.publish", "orders", b"\x01\x02pay")
    assert protocol.encode_command_frame(env) == lib.encode_command_frame(
        b"stream.publish", b"orders", b"\x01\x02pay"
    )
    tid, sid = "e5" * 16, "f6" * 8
    for sampled in (True, False):
        traced = protocol.CommandEnvelope(
            "saga.start", "order-1", b"pp", (tid, sid, sampled)
        )
        assert protocol.encode_command_frame(traced) == lib.encode_command_frame_traced(
            b"saga.start", b"order-1", b"pp", tid.encode(), sid.encode(), sampled
        )
    # Decode: untraced 4-tuple, traced 7-tuple (trace triple appended,
    # symmetric with the request shapes).
    framed = protocol.encode_command_frame(env)
    assert lib.decode_inbound(framed[4:]) == (2, b"stream.publish", b"orders", b"\x01\x02pay")
    traced = protocol.CommandEnvelope("saga.start", "order-1", b"pp", (tid, sid, True))
    tframed = protocol.encode_command_frame(traced)
    assert lib.decode_inbound(tframed[4:]) == (
        2, b"saga.start", b"order-1", b"pp", tid.encode(), sid.encode(), True,
    )
    # Python typed decode agrees with both.
    back = protocol.decode_inbound(tframed[4:])
    assert type(back) is protocol.CommandEnvelope and back == traced


def test_qos_request_frame_parity():
    """The appended QoS fields (tenant/priority/deadline_ms, ISSUE 20) keep
    byte parity at every arity: default-field envelopes stay on the
    legacy/traced encoders byte-identical, classified ones match the new
    entry point with trailing-default truncation."""
    if not lib.has_qos:
        pytest.skip("prebuilt native lib predates QoS frames")
    tid, sid = "a7" * 16, "b8" * 8
    cases = [
        # (env, (tid, sid, sampled, tenant, priority, deadline_ms))
        (protocol.RequestEnvelope("S", "i", "M", b"p", tenant="bulk"),
         (b"", b"", -1, b"bulk", 0, 0)),
        (protocol.RequestEnvelope("S", "i", "M", b"p", priority=2),
         (b"", b"", -1, b"", 2, 0)),
        (protocol.RequestEnvelope("S", "i", "M", b"p", deadline_ms=1500),
         (b"", b"", -1, b"", 0, 1500)),
        (protocol.RequestEnvelope("S", "i", "M", b"p", tenant="t", priority=1,
                                  deadline_ms=99999),
         (b"", b"", -1, b"t", 1, 99999)),
        (protocol.RequestEnvelope("S", "i", "M", b"p", (tid, sid, True),
                                  tenant="iact", priority=3, deadline_ms=250),
         (tid.encode(), sid.encode(), 1, b"iact", 3, 250)),
        (protocol.RequestEnvelope("S", "i", "M", b"p", (tid, sid, False),
                                  tenant="iact"),
         (tid.encode(), sid.encode(), 0, b"iact", 0, 0)),
    ]
    for env, (t, s, sampled, tenant, prio, dl) in cases:
        assert protocol.encode_request_frame(env) == lib.encode_request_frame_qos(
            b"S", b"i", b"M", b"p", t, s, sampled, tenant, prio, dl
        ), env
    # All-default QoS fields: byte-identical to the pre-QoS layouts.
    env = protocol.RequestEnvelope("S", "i", "M", b"p", tenant="", priority=0,
                                   deadline_ms=0)
    assert protocol.encode_request_frame(env) == lib.encode_request_frame(
        b"S", b"i", b"M", b"p"
    )
    traced = protocol.RequestEnvelope("S", "i", "M", b"p", (tid, sid, True))
    assert protocol.encode_request_frame(traced) == lib.encode_request_frame_traced(
        b"S", b"i", b"M", b"p", tid.encode(), sid.encode(), True
    )


def test_qos_decode_inbound_parity():
    if not lib.has_qos:
        pytest.skip("prebuilt native lib predates QoS frames")
    tid, sid = "c9" * 16, "d0" * 8
    env = protocol.RequestEnvelope(
        "S", "i", "M", b"xyz", (tid, sid, True), tenant="bulk", priority=2,
        deadline_ms=750,
    )
    framed = protocol.encode_request_frame(env)
    assert lib.decode_inbound_qos(framed[4:]) == (
        0, b"S", b"i", b"M", b"xyz", tid.encode(), sid.encode(), True,
        b"bulk", 2, 750,
    )
    # Untraced-but-classified: the wire carries a nil trace slot; the
    # decoder reports sampled=None and empty trace spans.
    untr = protocol.RequestEnvelope("S", "i", "M", b"x", tenant="t", deadline_ms=9)
    assert lib.decode_inbound_qos(protocol.encode_request_frame(untr)[4:]) == (
        0, b"S", b"i", b"M", b"x", b"", b"", None, b"t", 0, 9,
    )
    # Legacy arities decode through the QoS entry point with defaults.
    legacy = protocol.encode_request_frame(protocol.RequestEnvelope("S", "i", "M", b"x"))
    assert lib.decode_inbound_qos(legacy[4:]) == (
        0, b"S", b"i", b"M", b"x", b"", b"", None, b"", 0, 0,
    )
    # Subscribe/command frames delegate to the legacy decoder unchanged.
    sub = protocol.encode_subscribe_frame(protocol.SubscriptionRequest("S", "j"))
    assert lib.decode_inbound_qos(sub[4:]) == (1, b"S", b"j")
    # Python typed decode agrees on every QoS field.
    back = protocol.decode_inbound(framed[4:])
    assert back == env and (back.tenant, back.priority, back.deadline_ms) == (
        "bulk", 2, 750,
    )


def test_native_frame_reader_parity():
    frames_in = [
        protocol.encode_request_frame(protocol.RequestEnvelope("A", "b", "C", b"d")),
        codec.frame(b""),
        codec.frame(b"x" * 100_000),
    ]
    stream = b"".join(frames_in)
    for chunk in (1, 3, 7, 4096):
        nat = native.NativeFrameReader(lib)
        py = codec.FrameReader()
        got_nat, got_py = [], []
        for i in range(0, len(stream), chunk):
            got_nat += nat.feed(stream[i : i + chunk])
            got_py += py.feed(stream[i : i + chunk])
        assert got_nat == got_py
        assert got_nat == [f[4:] for f in frames_in]


def test_native_frame_reader_oversize():
    from rio_tpu.errors import SerializationError

    nat = native.NativeFrameReader(lib)
    with pytest.raises(SerializationError):
        nat.feed(b"\xff\xff\xff\xff")


# ---------------------------------------------------------------------------
# Native transport integration (mirrors test_client_server shapes)
# ---------------------------------------------------------------------------


@message
class Ask:
    text: str = ""


@message
class Answer:
    text: str = ""
    times: int = 0


@message
class Publish:
    text: str = ""


@message
class Slow:
    delay_ms: int = 0


@wire_error
class NativeUnanswerable(Exception):
    pass


class NativeOracle(ServiceObject):
    def __init__(self):
        self.times = 0

    @handler
    async def ask(self, msg: Ask, ctx: AppData) -> Answer:
        if msg.text == "unanswerable":
            raise NativeUnanswerable(msg.text, 42)
        if msg.text == "panic":
            raise RuntimeError("boom")
        self.times += 1
        return Answer(text=f"echo:{msg.text}", times=self.times)

    @handler
    async def slow(self, msg: Slow, ctx: AppData) -> Answer:
        await asyncio.sleep(msg.delay_ms / 1000.0)
        self.times += 1
        return Answer(text="slow", times=self.times)

    @handler
    async def publish(self, msg: Publish, ctx: AppData) -> Answer:
        from rio_tpu.registry import type_id

        router = ctx.get(MessageRouter)
        router.publish(type_id(NativeOracle), self.id, Publish(text=f"pub:{msg.text}"))
        return Answer(text="published")


def build_registry() -> Registry:
    r = Registry()
    r.add_type(NativeOracle)
    return r


def test_native_request_response():
    async def body(cluster: Cluster):
        client = cluster.client()
        out = await client.send(NativeOracle, "o1", Ask(text="hi"), returns=Answer)
        assert out == Answer(text="echo:hi", times=1)
        out = await client.send(NativeOracle, "o1", Ask(text="again"), returns=Answer)
        assert out.times == 2
        client.close()

    asyncio.run(
        run_integration_test(
            body, registry_builder=build_registry, num_servers=2, transport="native"
        )
    )


def test_native_typed_error_and_panic_isolation():
    async def body(cluster: Cluster):
        client = cluster.client()
        with pytest.raises(NativeUnanswerable) as ei:
            await client.send(NativeOracle, "o", Ask(text="unanswerable"), returns=Answer)
        assert ei.value.args == ("unanswerable", 42)
        out = await client.send(NativeOracle, "o", Ask(text="ok"), returns=Answer)
        assert out.times == 1  # object survived the typed error
        client.close()

    asyncio.run(
        run_integration_test(
            body, registry_builder=build_registry, num_servers=2, transport="native"
        )
    )


def test_native_redirect_across_servers():
    async def body(cluster: Cluster):
        c1 = cluster.client()
        for i in range(12):
            await c1.send(NativeOracle, f"o{i}", Ask(text="seed"), returns=Answer)
        # Fresh client, cold cache: random picks must get redirected.
        c2 = cluster.client()
        for i in range(12):
            out = await c2.send(NativeOracle, f"o{i}", Ask(text="q"), returns=Answer)
            assert out.times == 2
        c1.close()
        c2.close()

    asyncio.run(
        run_integration_test(
            body, registry_builder=build_registry, num_servers=5, transport="native"
        )
    )


def test_native_pubsub():
    async def body(cluster: Cluster):
        client = cluster.client()
        # Allocate first so the subscription lands on the host.
        await client.send(NativeOracle, "caster", Ask(text="warm"), returns=Answer)
        stream = await client.subscribe(NativeOracle, "caster")
        got: list[str] = []
        ready = asyncio.Event()

        async def consume():
            async for item in stream:
                got.append(item.text)
                ready.set()
                if len(got) >= 2:
                    return

        task = asyncio.create_task(consume())
        await asyncio.sleep(0.2)  # let the subscription attach
        await client.send(NativeOracle, "caster", Publish(text="a"), returns=Answer)
        await asyncio.wait_for(ready.wait(), 5)
        await client.send(NativeOracle, "caster", Publish(text="b"), returns=Answer)
        await asyncio.wait_for(task, 5)
        assert got == ["pub:a", "pub:b"]
        client.close()

    asyncio.run(
        run_integration_test(
            body, registry_builder=build_registry, num_servers=2, transport="native"
        )
    )


def test_native_mixed_transports_interop():
    """A cluster of one native + one asyncio node serves the same traffic."""

    async def body(cluster: Cluster):
        client = cluster.client()
        for i in range(8):
            out = await client.send(NativeOracle, f"m{i}", Ask(text="x"), returns=Answer)
            assert out.times == 1
        client.close()

    async def run():
        from rio_tpu import LocalObjectPlacement, LocalStorage, Server
        from rio_tpu.cluster.membership_protocol import LocalClusterProvider

        members = LocalStorage()
        placement = LocalObjectPlacement()
        servers = []
        for transport in ("native", "asyncio"):
            server = Server(
                address="127.0.0.1:0",
                registry=build_registry(),
                cluster_provider=LocalClusterProvider(members),
                object_placement_provider=placement,
                transport=transport,
            )
            await server.prepare()
            await server.bind()
            servers.append(server)
        tasks = [asyncio.create_task(s.run()) for s in servers]
        try:
            from .server_utils import wait_for_active_members

            await wait_for_active_members(members, 2)
            await body(Cluster(servers=servers, members=members, placement=placement))
        finally:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

    asyncio.run(run())


def test_native_client_engine_roundtrips():
    """Client with transport='native': sockets + framing on the C++ engine."""

    async def body(cluster: Cluster):
        client = cluster.client(transport="native")
        assert client._client_engine is not None
        for i in range(10):
            out = await client.send(NativeOracle, "ne", Ask(text=f"m{i}"), returns=Answer)
            assert out.times == i + 1
        client.close()

    asyncio.run(
        run_integration_test(
            body, registry_builder=build_registry, num_servers=2, transport="native"
        )
    )


def test_native_client_redirects_and_connect_failure():
    async def body(cluster: Cluster):
        c1 = cluster.client(transport="native")
        for i in range(8):
            await c1.send(NativeOracle, f"r{i}", Ask(text="seed"), returns=Answer)
        c2 = cluster.client(transport="native")
        for i in range(8):
            out = await c2.send(NativeOracle, f"r{i}", Ask(text="q"), returns=Answer)
            assert out.times == 2
        # Connect to a dead port must raise cleanly through the engine.
        from rio_tpu.errors import ServerNotAvailable
        import pytest as _pytest

        with _pytest.raises(ServerNotAvailable):
            await c1._client_engine.connect("127.0.0.1", 9, 0.5)
        c1.close()
        c2.close()

    asyncio.run(
        run_integration_test(
            body, registry_builder=build_registry, num_servers=4, transport="asyncio"
        )
    )


def test_native_client_subscription():
    """Subscriptions ride the client engine end-to-end."""

    async def body(cluster: Cluster):
        client = cluster.client(transport="native")
        await client.send(NativeOracle, "nsub", Ask(text="warm"), returns=Answer)
        stream = await client.subscribe(NativeOracle, "nsub")
        got: list[str] = []

        async def consume():
            async for item in stream:
                got.append(item.text)
                if len(got) >= 3:
                    return

        task = asyncio.create_task(consume())
        await asyncio.sleep(0.2)
        for i in range(3):
            await client.send(NativeOracle, "nsub", Publish(text=f"s{i}"), returns=Answer)
        await asyncio.wait_for(task, 5)
        assert got == ["pub:s0", "pub:s1", "pub:s2"]
        client.close()

    asyncio.run(
        run_integration_test(
            body, registry_builder=build_registry, num_servers=2, transport="native"
        )
    )


def test_coalesced_egress_buffer_parity():
    """A coalesced egress wave — N complete length-prefixed response frames
    joined into ONE buffer (what `_flush_ready` now hands the engine, and
    what the engine's sendmsg gather puts on the socket) — must split back
    into exactly the same frames as N separate writes, in both frame
    readers. Coalescing may never be observable above the framing layer."""
    frames = [
        codec.frame(protocol.ResponseEnvelope.ok(b"r%d" % i).to_bytes())
        for i in range(9)
    ]
    frames.append(
        codec.frame(
            protocol.ResponseEnvelope.err(
                protocol.ResponseError.redirect("1.2.3.4:5")
            ).to_bytes()
        )
    )
    frames.append(lib.encode_response_ok_frame(b"x" * 70_000))
    frames.append(codec.frame(b""))  # empty payload mid-wave
    wave = b"".join(frames)
    expect = [f[4:] for f in frames]
    # Single joined feed.
    assert native.NativeFrameReader(lib).feed(wave) == expect
    assert codec.FrameReader().feed(wave) == expect
    # Chunked feed (waves split mid-frame by the kernel) stays in parity.
    for chunk in (1, 13, 1337):
        nat, py = native.NativeFrameReader(lib), codec.FrameReader()
        got_nat: list = []
        got_py: list = []
        for i in range(0, len(wave), chunk):
            got_nat += nat.feed(wave[i : i + chunk])
            got_py += py.feed(wave[i : i + chunk])
        assert got_nat == got_py == expect


@pytest.mark.parametrize("coalesce", [True, False])
def test_native_pipelined_wave_coalesce_ab(coalesce, monkeypatch):
    """Pipelined burst whose HEAD response finishes last: every later
    response parks in resp_q, so the head's done-callback flushes the whole
    wave at once — one joined engine.send when coalescing is on, N sends
    when off. Client-visible behavior must be identical either way."""
    from rio_tpu.native import transport as nt

    monkeypatch.setattr(nt, "_EGRESS_COALESCE", coalesce)

    async def body(cluster: Cluster):
        client = cluster.client()
        # Warm placements so the burst pipelines on one pooled connection.
        for i in range(16):
            await client.send(NativeOracle, f"w{i}", Ask(text="warm"), returns=Answer)
        outs = await asyncio.gather(
            client.send(NativeOracle, "w0", Slow(delay_ms=150), returns=Answer),
            *(
                client.send(NativeOracle, f"w{i}", Ask(text=f"m{i}"), returns=Answer)
                for i in range(1, 16)
            ),
        )
        assert outs[0].text == "slow"
        assert [o.text for o in outs[1:]] == [f"echo:m{i}" for i in range(1, 16)]
        client.close()

    asyncio.run(
        run_integration_test(
            body, registry_builder=build_registry, num_servers=1, transport="native"
        )
    )


def test_native_frame_reader_fuzz_parity():
    """Seeded fuzz: random valid frames interleaved with random garbage,
    fed in random chunk sizes — the C++ reader must match the Python
    reader byte for byte, including WHERE the oversize error fires
    (garbage bytes routinely parse as absurd length prefixes)."""
    import random

    from rio_tpu.errors import SerializationError

    rng = random.Random(0xBEEF)
    for _trial in range(25):
        parts = []
        for _ in range(rng.randrange(1, 12)):
            if rng.random() < 0.6:
                body = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 300)))
                parts.append(codec.frame(body))
            else:
                parts.append(bytes(rng.randrange(256) for _ in range(rng.randrange(1, 40))))
        stream = b"".join(parts)
        nat = native.NativeFrameReader(lib)
        py = codec.FrameReader()
        i = 0
        while i < len(stream):
            n = rng.randrange(1, 97)
            chunk = stream[i : i + n]
            i += n
            err_nat = err_py = False
            out_nat = out_py = None
            try:
                out_nat = nat.feed(chunk)
            except SerializationError:
                err_nat = True
            try:
                out_py = py.feed(chunk)
            except SerializationError:
                err_py = True
            assert err_nat == err_py, f"error divergence at byte {i}"
            if err_nat:
                break
            assert out_nat == out_py, f"frame divergence at byte {i}"
