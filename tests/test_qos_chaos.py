"""QoS deadline propagation under injected transport latency (chaos).

The deterministic tests in ``tests/test_qos.py`` pin the mechanism; this
lane pins the INVARIANTS when real latency eats the budget at every hop
(seeded :class:`~rio_tpu.faults.TransportFaults` frame delays, so a red
run reproduces with the same seed):

* a handler never observes MORE budget than the client sent — latency
  only ever drains it, nothing along the path invents time;
* a deadline-carrying request never arrives with its deadline stripped
  (``scope_budget_ms() == 0`` would mean a hop dropped the field);
* tight budgets under fat links surface as ``DeadlineExceeded`` at the
  client — and every server-side drop happened *before* a handler ran
  (``deadline_drops`` moves, handler-run count does not);
* internal hops keep decrementing under latency: the downstream actor
  sees strictly less budget than the upstream request carried.

``RIO_TPU_CHAOS_SECS`` stretches the soak in the nightly matrix; the
default keeps the tier-1 lane fast.
"""

import asyncio
import os
import time

from rio_tpu import AppData, Registry, ServiceObject, handler
from rio_tpu.errors import DeadlineExceeded, RetryExhausted
from rio_tpu.faults import LinkRule, TransportFaults
from rio_tpu.qos import QosConfig

from .server_utils import Cluster, run_integration_test
from .test_qos import (
    HopProbe,
    Probe,
    ProbeOut,
    ScopeReporter,
    build_qos_registry,
)

CHAOS_SECS = float(os.environ.get("RIO_TPU_CHAOS_SECS", "3"))


def _delayed_faults(seed: int, delay: float) -> TransportFaults:
    tf = TransportFaults(seed=seed)
    tf.add_rule(LinkRule(delay=delay))
    return tf


def test_budget_never_inflates_under_injected_latency():
    async def body(cluster: Cluster):
        client = cluster.client(
            transport_faults=_delayed_faults(seed=7, delay=0.015)
        )
        try:
            deadline = time.monotonic() + CHAOS_SECS
            sent_budget = 2000
            ok = expired = 0
            i = 0
            while time.monotonic() < deadline:
                i += 1
                try:
                    out = await client.send(
                        ScopeReporter, f"c{i % 8}", Probe(),
                        returns=ProbeOut, tenant="chaos",
                        deadline_ms=sent_budget,
                    )
                except (DeadlineExceeded, RetryExhausted):
                    expired += 1
                    continue
                ok += 1
                # Latency drained the budget but never inflated or
                # stripped it.
                assert 0 < out.budget_ms <= sent_budget
                assert out.tenant == "chaos"
            # 15 ms/frame against a 2 s budget: the flood mostly lands.
            assert ok > 0
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_qos_registry,
            num_servers=2,
            server_kwargs={"qos_config": QosConfig()},
        )
    )


def test_tight_budgets_expire_cleanly_under_latency_and_contention():
    async def body(cluster: Cluster):
        # One handler slot + concurrent slow requests + frame delay: most
        # budgets die parked in the class queue. The contract is they die
        # as DEADLINE verdicts before their handler starts — never as a
        # handler running on spent time.
        client = cluster.client(
            transport_faults=_delayed_faults(seed=11, delay=0.01)
        )
        server = cluster.servers[0]
        spent_seen = ok = expired = 0

        async def one(i: int):
            nonlocal spent_seen, ok, expired
            try:
                out = await client.send(
                    ScopeReporter, f"t{i % 6}", Probe(sleep_s=0.05),
                    returns=ProbeOut, deadline_ms=80,
                )
            except (DeadlineExceeded, RetryExhausted):
                expired += 1
                return
            ok += 1
            if out.budget_ms < 0:
                spent_seen += 1

        try:
            deadline = time.monotonic() + CHAOS_SECS
            i = 0
            while time.monotonic() < deadline:
                await asyncio.gather(*(one(i + k) for k in range(6)))
                i += 6
            assert expired > 0  # contention really ate budgets
            # Every server-side death was a pre-handler drop...
            assert server.qos.stats.deadline_drops > 0
            # ...and no handler ever observed an already-spent scope —
            # that would mean the admission layer ran doomed work.
            assert spent_seen == 0
            assert client.stats.deadline_exceeded >= expired
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_qos_registry,
            num_servers=1,
            server_kwargs={"qos_config": QosConfig(max_concurrent=1)},
        )
    )


def test_internal_hop_keeps_decrementing_under_latency():
    async def body(cluster: Cluster):
        client = cluster.client(
            transport_faults=_delayed_faults(seed=23, delay=0.01)
        )
        try:
            await client.send(ScopeReporter, "a", Probe(), returns=ProbeOut)
            await client.send(ScopeReporter, "b", Probe(), returns=ProbeOut)
            deadline = time.monotonic() + CHAOS_SECS
            hops = refused = 0
            while time.monotonic() < deadline:
                try:
                    out = await client.send(
                        ScopeReporter, "a",
                        HopProbe(target_id="b", sleep_s=0.02),
                        returns=ProbeOut, tenant="hopper", deadline_ms=1000,
                    )
                except (DeadlineExceeded, RetryExhausted):
                    continue
                if out.tenant == "refused":
                    refused += 1  # budget died exactly at the hop — legal
                    continue
                hops += 1
                # The 20 ms burned upstream (plus link latency) is always
                # visible downstream; classification survives the hop.
                assert 0 < out.budget_ms <= 1000 - 20
                assert out.tenant == "hopper"
            assert hops > 0
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_qos_registry,
            num_servers=1,
            server_kwargs={"qos_config": QosConfig()},
        )
    )
