"""Golden-wire conformance: the EXACT statements the Postgres and Redis
backends emit for a standard op matrix, committed as golden files.

The fakes (tests/fake_pg.py, tests/fake_redis.py) already make the real
backend code paths execute in this environment; this module additionally
pins what crosses the driver boundary — every SQL statement (with bound
params) reaching the DBAPI cursor, every RESP2 command array reaching the
server — byte for byte. A schema migration, a changed WHERE clause, a
reordered pipeline, or a new roundtrip on a hot path shows up as a golden
diff and has to be a conscious decision.

Regenerate after an intentional wire change::

    RIO_TPU_REGEN_GOLDEN=1 python -m pytest tests/test_golden_wire.py

then review the golden diff like any other code change.
"""

from __future__ import annotations

import difflib
import os
import pathlib

import pytest

from rio_tpu.cluster.storage import Member
from rio_tpu.object_placement import ObjectId, ObjectPlacementItem
from rio_tpu.utils.resp import RedisClient

from .fake_redis import FakeRedisServer

GOLDEN_DIR = pathlib.Path(__file__).parent / "golden"

# Member.push / notify_failure stamp time.time() into stored values; the
# matrix freezes it so the captured wire bytes are run-independent.
FROZEN_TIME = 1700000000.0

# Connection-handshake commands are pool-shape dependent (how many conns
# the client opens, and when, is an implementation detail of the pool, not
# of the backends under test) — they are filtered from the RESP capture.
HANDSHAKE = {"PING", "SELECT", "AUTH", "FLUSHDB"}


def _assert_golden(name: str, text: str) -> None:
    path = GOLDEN_DIR / name
    if os.environ.get("RIO_TPU_REGEN_GOLDEN"):
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(text)
        return
    assert path.exists(), (
        f"missing golden file {path} — run with RIO_TPU_REGEN_GOLDEN=1 to create"
    )
    expected = path.read_text()
    if text != expected:
        diff = "\n".join(
            difflib.unified_diff(
                expected.splitlines(), text.splitlines(),
                fromfile=f"golden/{name}", tofile="captured", lineterm="",
            )
        )
        raise AssertionError(f"wire stream drifted from golden/{name}:\n{diff}")


async def _membership_matrix(storage, mark) -> None:
    mark("membership.prepare")
    await storage.prepare()
    mark("membership.push")
    await storage.push(Member(ip="10.0.0.1", port=5000, active=True))
    mark("membership.push_upsert")
    await storage.push(Member(ip="10.0.0.1", port=5000, active=True))
    mark("membership.push_shard_map")
    await storage.push(
        Member(ip="10.0.0.2", port=5001, active=True,
               shard_map="3|10.0.0.2:6000,10.0.0.2:6001")
    )
    mark("membership.members")
    await storage.members()
    mark("membership.active_members")
    await storage.active_members()
    mark("membership.is_active")
    await storage.is_active("10.0.0.1:5000")
    mark("membership.set_inactive")
    await storage.set_inactive("10.0.0.1", 5000)
    mark("membership.set_active")
    await storage.set_active("10.0.0.1", 5000)
    mark("membership.notify_failure")
    await storage.notify_failure("10.0.0.1", 5000)
    mark("membership.member_failures")
    await storage.member_failures("10.0.0.1", 5000)
    mark("membership.remove")
    await storage.remove("10.0.0.1", 5000)


async def _placement_matrix(p, mark) -> None:
    oid = ObjectId("Svc", "g1")
    mark("placement.prepare")
    await p.prepare()
    mark("placement.update")
    await p.update(ObjectPlacementItem(oid, "h1:1"))
    mark("placement.lookup")
    await p.lookup(oid)
    mark("placement.update_move")
    await p.update(ObjectPlacementItem(oid, "h2:2"))
    mark("placement.update_batch")
    await p.update_batch(
        [ObjectPlacementItem(ObjectId("Svc", f"b{i}"), "h3:3") for i in range(2)]
    )
    mark("placement.lookup_batch")
    await p.lookup_batch([ObjectId("Svc", "b0"), ObjectId("Svc", "b1")])
    mark("placement.items")
    await p.items()
    mark("placement.clean_server")
    await p.clean_server("h3:3")
    # Replication directory rows: epoch-preserving set, fenced CAS (one
    # losing attempt, one winning), then removal.
    mark("placement.set_standbys")
    await p.set_standbys(oid, ["s1:1", "s2:2"])
    mark("placement.standbys")
    await p.standbys(oid)
    mark("placement.promote_standby_lose")
    await p.promote_standby(oid, "s1:1", 7)
    mark("placement.promote_standby_win")
    await p.promote_standby(oid, "s1:1", 0)
    mark("placement.remove")
    await p.remove(oid)


@pytest.mark.asyncio
async def test_postgres_wire_golden(monkeypatch):
    from tests import fake_pg

    fake_pg.install()
    fake_pg.reset()
    monkeypatch.setattr("rio_tpu.cluster.storage.sqlite.time.time",
                        lambda: FROZEN_TIME)

    log: list[tuple[str, ...]] = []
    orig_execute = fake_pg.FakeCursor.execute

    def spy(self, sql, params=()):
        log.append(("sql", sql, repr(tuple(params or ()))))
        return orig_execute(self, sql, params)

    monkeypatch.setattr(fake_pg.FakeCursor, "execute", spy)

    from rio_tpu.cluster.storage.postgres import PostgresMembershipStorage
    from rio_tpu.object_placement.postgres import PostgresObjectPlacement

    dsn = "postgresql://fake-pg/golden-wire"
    await _membership_matrix(
        PostgresMembershipStorage(dsn), lambda op: log.append(("op", op))
    )
    await _placement_matrix(
        PostgresObjectPlacement(dsn), lambda op: log.append(("op", op))
    )

    lines: list[str] = []
    for entry in log:
        if entry[0] == "op":
            lines.append(f"== {entry[1]}")
        else:
            _, sql, params = entry
            lines.append(" ".join(sql.split()))
            lines.append(f"-- params={params}")
    _assert_golden("postgres_wire.txt", "\n".join(lines) + "\n")


@pytest.mark.asyncio
async def test_redis_wire_golden(monkeypatch):
    monkeypatch.setattr("rio_tpu.cluster.storage.redis.time.time",
                        lambda: FROZEN_TIME)

    server = await FakeRedisServer().start()
    log: list[tuple[str, ...]] = []
    # Spy at the wire entry point (_handle), not command execution
    # (_dispatch): transaction control (WATCH/MULTI/EXEC) and queued
    # commands are then captured once each, in the order they cross the
    # socket — which is the stream this golden pins.
    orig_handle = FakeRedisServer._handle

    def spy(self, session, cmd):
        name = cmd[0].decode().upper()
        if name not in HANDSHAKE:
            log.append(
                ("cmd", " ".join(c.decode("utf-8", "backslashreplace")
                                 for c in cmd))
            )
        return orig_handle(self, session, cmd)

    monkeypatch.setattr(FakeRedisServer, "_handle", spy)
    try:
        from rio_tpu.cluster.storage.redis import RedisMembershipStorage
        from rio_tpu.object_placement.redis import RedisObjectPlacement

        client = RedisClient("127.0.0.1", server.port)
        await _membership_matrix(
            RedisMembershipStorage(client, key_prefix="g_mem"),
            lambda op: log.append(("op", op)),
        )
        await _placement_matrix(
            RedisObjectPlacement(client, key_prefix="g_place"),
            lambda op: log.append(("op", op)),
        )
        client.close()
    finally:
        await server.stop()

    lines = [
        f"== {e[1]}" if e[0] == "op" else e[1]
        for e in log
    ]
    _assert_golden("redis_wire.txt", "\n".join(lines) + "\n")


def test_shard_map_membership_rows_golden():
    """Pin the shard-map membership column's wire compatibility contract.

    The shard map rides the membership row as an APPENDED column (PR 15),
    exactly like the load vector before it: a legacy (map-less) row and a
    shard-mapped row must both decode, legacy-length values written by old
    nodes must parse with ``shard_map == ""``, and the redirect frames a
    legacy (non-shard-aware) client follows must be byte-identical whether
    or not the cluster advertises a map — shard awareness is purely a
    client-side read of a column legacy decoders skip.
    """
    from rio_tpu.cluster.storage.redis import RedisMembershipStorage
    from rio_tpu.commands import ShardMap
    from rio_tpu.protocol import (
        ResponseEnvelope,
        ResponseError,
        encode_response_frame,
    )

    enc = RedisMembershipStorage._encode
    dec = RedisMembershipStorage._decode

    legacy = Member(ip="10.0.0.1", port=5000, active=True,
                    last_seen=FROZEN_TIME)
    mapped = Member(ip="10.0.0.2", port=5001, active=True,
                    last_seen=FROZEN_TIME,
                    shard_map="3|10.0.0.2:6000,10.0.0.2:6001")

    lines = [
        f"== member.legacy\n{enc(legacy)}",
        f"== member.shard_mapped\n{enc(mapped)}",
        # Value a pre-shard-map node wrote (5 fields) and the pre-load
        # 4-field ancestor: both must stay decodable forever.
        "== member.legacy_5field\n10.0.0.1;5000;1;1700000000.0;",
        "== member.legacy_4field\n10.0.0.1;5000;1;1700000000.0",
    ]

    redirect = encode_response_frame(
        ResponseEnvelope.err(ResponseError.redirect("10.0.0.2:6001"))
    )
    lines.append(f"== redirect.frame ({len(redirect)} bytes)")
    for off in range(0, len(redirect), 16):
        lines.append(f"{off:04x}  {redirect[off : off + 16].hex(' ')}")
    _assert_golden("shard_map_rows.txt", "\n".join(lines) + "\n")

    # Decode symmetry + tolerant short-row parsing.
    assert dec(enc(legacy).encode()) == legacy
    assert dec(enc(mapped).encode()) == mapped
    assert dec(b"10.0.0.1;5000;1;1700000000.0;").shard_map == ""
    assert dec(b"10.0.0.1;5000;1;1700000000.0").shard_map == ""
    # The advertised map round-trips through the row into a usable router.
    m = ShardMap.decode(dec(enc(mapped).encode()).shard_map)
    assert m is not None and m.epoch == 3 and len(m.slots) == 2
    # Garbage in the column degrades to "no map", never an exception.
    assert ShardMap.decode("") is None
    assert ShardMap.decode("not-a-map") is None
    assert ShardMap.decode("x|10.0.0.1:1") is None


def test_dump_events_frame_golden():
    """Pin the rio.Admin journal-scrape frames byte for byte.

    DUMP_EVENTS is an operator-facing wire surface (the CLI and any
    external tooling speak it to arbitrary-version nodes), so the exact
    msgpack layout of the request envelope and the EventsSnapshot response
    — including the positional JournalEvent row shape — is a compatibility
    contract: rows may only ever GROW by appending trailing fields
    (JournalEvent.from_row tolerates short rows; see MIGRATING.md).
    """
    from rio_tpu import codec
    from rio_tpu.admin import ADMIN_TYPE, DumpEvents, EventsSnapshot
    from rio_tpu.journal import JournalEvent
    from rio_tpu.protocol import (
        RequestEnvelope,
        ResponseEnvelope,
        encode_request_frame,
        encode_response_frame,
    )

    request = encode_request_frame(
        RequestEnvelope(
            handler_type=ADMIN_TYPE,
            handler_id="10.0.0.1:5000",
            message_type="rio.DumpEvents",
            payload=codec.serialize(
                DumpEvents(
                    kinds=["migrate_pin", "replica_promote"],
                    key="Svc/g1",
                    since_seq=7,
                    limit=64,
                )
            ),
        )
    )
    snapshot = EventsSnapshot(
        address="10.0.0.1:5000",
        node_seq=9,
        dropped=1,
        rows=[
            JournalEvent(
                seq=8,
                wall_ts=FROZEN_TIME,
                mono_ts=12.5,
                node="10.0.0.1:5000",
                epoch=3,
                kind="migrate_pin",
                key="Svc/g1",
                attrs={"target": "10.0.0.2:5000"},
                trace_id="ab" * 16,
            ).to_row(),
            JournalEvent(
                seq=9,
                wall_ts=FROZEN_TIME + 0.25,
                mono_ts=12.75,
                node="10.0.0.1:5000",
                epoch=4,
                kind="replica_promote",
                key="Svc/g1",
            ).to_row(),
        ],
    )
    response = encode_response_frame(
        ResponseEnvelope(body=codec.serialize(snapshot))
    )

    def hexdump(label: str, frame: bytes) -> list[str]:
        lines = [f"== {label} ({len(frame)} bytes)"]
        for off in range(0, len(frame), 16):
            chunk = frame[off : off + 16]
            lines.append(f"{off:04x}  {chunk.hex(' ')}")
        return lines

    text = "\n".join(hexdump("dump_events.request", request)
                     + hexdump("dump_events.response", response)) + "\n"
    _assert_golden("dump_events_frames.txt", text)

    # The pinned bytes must still decode to the same snapshot (a golden
    # that drifts AND round-trips is a wire version bump, not a bug).
    back = codec.deserialize(codec.serialize(snapshot), EventsSnapshot)
    assert [e.seq for e in back.events()] == [8, 9]
    assert back.events()[0].attrs == {"target": "10.0.0.2:5000"}


def test_dump_series_frame_golden():
    """Pin the rio.Admin time-series-scrape frames byte for byte.

    DUMP_SERIES is the second operator-facing admin scrape (the ``watch``
    CLI and trend tooling speak it to arbitrary-version nodes); the
    request envelope and the SeriesSnapshot response — including the
    positional SeriesSample row shape — are a compatibility contract:
    rows may only ever GROW by appending trailing fields
    (SeriesSample.from_row tolerates short rows; see MIGRATING.md).
    """
    from rio_tpu import codec
    from rio_tpu.admin import ADMIN_TYPE, DumpSeries, SeriesSnapshot
    from rio_tpu.protocol import (
        RequestEnvelope,
        ResponseEnvelope,
        encode_request_frame,
        encode_response_frame,
    )
    from rio_tpu.timeseries import SeriesSample

    request = encode_request_frame(
        RequestEnvelope(
            handler_type=ADMIN_TYPE,
            handler_id="10.0.0.1:5000",
            message_type="rio.DumpSeries",
            payload=codec.serialize(
                DumpSeries(
                    names=["rio.load.", "rio.handler.Svc.Get.p99_ms"],
                    since_seq=3,
                    limit=120,
                )
            ),
        )
    )
    snapshot = SeriesSnapshot(
        address="10.0.0.1:5000",
        node_seq=5,
        dropped=2,
        rows=[
            SeriesSample(
                seq=4,
                wall_ts=FROZEN_TIME,
                mono_ts=41.5,
                node="10.0.0.1:5000",
                gauges={"rio.load.inflight": 3.0, "rio.load.sheds": 0.0},
            ).to_row(),
            SeriesSample(
                seq=5,
                wall_ts=FROZEN_TIME + 1.0,
                mono_ts=42.5,
                node="10.0.0.1:5000",
                gauges={"rio.load.inflight": 5.0, "rio.load.sheds": 1.0},
            ).to_row(),
        ],
        meta={"solver_mode": "sinkhorn+delta", "alerts": []},
    )
    response = encode_response_frame(
        ResponseEnvelope(body=codec.serialize(snapshot))
    )

    def hexdump(label: str, frame: bytes) -> list[str]:
        lines = [f"== {label} ({len(frame)} bytes)"]
        for off in range(0, len(frame), 16):
            chunk = frame[off : off + 16]
            lines.append(f"{off:04x}  {chunk.hex(' ')}")
        return lines

    text = "\n".join(hexdump("dump_series.request", request)
                     + hexdump("dump_series.response", response)) + "\n"
    _assert_golden("dump_series_frames.txt", text)

    back = codec.deserialize(codec.serialize(snapshot), SeriesSnapshot)
    assert [s.seq for s in back.samples()] == [4, 5]
    assert back.samples()[1].gauges["rio.load.sheds"] == 1.0
    assert back.meta["solver_mode"] == "sinkhorn+delta"
    # Tolerant decode: a short legacy row (no gauges) still parses.
    legacy = SeriesSample.from_row([1, FROZEN_TIME, 40.0])
    assert legacy.seq == 1 and legacy.node == "" and legacy.gauges == {}


def test_dump_spans_frame_golden():
    """Pin the rio.Admin span-scrape frames byte for byte.

    DUMP_SPANS is the third operator-facing admin scrape (the ``trace``
    CLI assembles cross-node waterfalls over it, against arbitrary-version
    nodes); the request envelope and the SpansSnapshot response — including
    the positional SpanRecord row shape — are a compatibility contract:
    rows may only ever GROW by appending trailing fields
    (SpanRecord.from_row tolerates short rows; see MIGRATING.md).
    """
    from rio_tpu import codec
    from rio_tpu.admin import ADMIN_TYPE, DumpSpans, SpansSnapshot
    from rio_tpu.protocol import (
        RequestEnvelope,
        ResponseEnvelope,
        encode_request_frame,
        encode_response_frame,
    )
    from rio_tpu.spans import SpanRecord

    request = encode_request_frame(
        RequestEnvelope(
            handler_type=ADMIN_TYPE,
            handler_id="10.0.0.1:5000",
            message_type="rio.DumpSpans",
            payload=codec.serialize(
                DumpSpans(trace_id="ab" * 16, since_seq=7, limit=64)
            ),
        )
    )
    snapshot = SpansSnapshot(
        address="10.0.0.1:5000",
        node_seq=9,
        dropped=1,
        rows=[
            SpanRecord(
                seq=8,
                trace_id="ab" * 16,
                span_id="cd" * 8,
                parent_id="ef" * 8,
                name="request",
                node="10.0.0.1:5000",
                wall_start=FROZEN_TIME,
                duration_us=1250,
                attrs={
                    "handler": "Svc/g1",
                    "msg": "Get",
                    "recv_us": 0,
                    "decode_us": 40,
                    "queue_us": 15,
                    "handler_us": 1100,
                    "encode_us": 30,
                    "flush_us": 65,
                },
            ).to_row(),
            SpanRecord(
                seq=9,
                trace_id="ab" * 16,
                span_id="0a" * 8,
                parent_id="cd" * 8,
                name="request",
                node="10.0.0.2:5000",
                wall_start=FROZEN_TIME + 0.5,
                duration_us=310,
                attrs={"handler": "Svc/g1", "msg": "Get", "status": 1},
            ).to_row(),
        ],
    )
    response = encode_response_frame(
        ResponseEnvelope(body=codec.serialize(snapshot))
    )

    def hexdump(label: str, frame: bytes) -> list[str]:
        lines = [f"== {label} ({len(frame)} bytes)"]
        for off in range(0, len(frame), 16):
            chunk = frame[off : off + 16]
            lines.append(f"{off:04x}  {chunk.hex(' ')}")
        return lines

    text = "\n".join(hexdump("dump_spans.request", request)
                     + hexdump("dump_spans.response", response)) + "\n"
    _assert_golden("dump_spans_frames.txt", text)

    back = codec.deserialize(codec.serialize(snapshot), SpansSnapshot)
    assert [r.seq for r in back.spans()] == [8, 9]
    assert back.spans()[0].attrs["handler_us"] == 1100
    assert back.spans()[1].parent_id == "cd" * 8  # hop nesting survives
    # Tolerant decode: a short legacy row still parses with defaults.
    legacy = SpanRecord.from_row([1, "t", "s"])
    assert legacy.seq == 1 and legacy.node == "" and legacy.attrs == {}


def test_admin_unknown_kind_acked_not_crashed():
    """Mixed-version clusters: an AdminRequest kind this server doesn't
    know (a NEWER tool speaking to an OLDER node) must answer a clean
    ``AdminAck(ok=False)`` on the wire — never an exception frame — so the
    scraping side can skip the node and continue over the survivors."""
    import asyncio

    from rio_tpu.admin import AdminAck, AdminControl, AdminRequest, AdminSender

    class _Sender:
        def send(self, cmd):  # pragma: no cover - unknown kinds never reach it
            raise AssertionError("unknown kind must not enqueue")

    class _Ctx:
        def try_get(self, t):
            return _Sender() if t is AdminSender else None

    ack = asyncio.run(
        AdminControl().admin(AdminRequest(kind="dump_holograms"), _Ctx())
    )
    assert isinstance(ack, AdminAck)
    assert ack.ok is False
    assert "dump_holograms" in ack.detail


def test_dump_edges_frame_golden():
    """Pin the rio.Admin edge-graph-scrape frames byte for byte.

    DUMP_EDGES is the affinity plane's operator scrape (the ``edges`` CLI
    and the placement feedback loop speak it to arbitrary-version nodes);
    the request envelope and the EdgesSnapshot response — including the
    positional edge row shape [src, dst, bytes_per_s, calls_per_s,
    local_frac] — are a compatibility contract: rows may only ever GROW
    by appending trailing fields (merge_edges reads by position and
    ignores extras).
    """
    from rio_tpu import codec
    from rio_tpu.admin import ADMIN_TYPE, DumpEdges, EdgesSnapshot
    from rio_tpu.protocol import (
        RequestEnvelope,
        ResponseEnvelope,
        encode_request_frame,
        encode_response_frame,
    )

    request = encode_request_frame(
        RequestEnvelope(
            handler_type=ADMIN_TYPE,
            handler_id="10.0.0.1:5000",
            message_type="rio.DumpEdges",
            payload=codec.serialize(DumpEdges(limit=64)),
        )
    )
    snapshot = EdgesSnapshot(
        address="10.0.0.1:5000",
        rows=[
            ["rio.StreamCursor.orders/fan", "Consumer.c1", 16384.0, 12.5, 1.0],
            ["rio.Saga.ord-7", "Inventory.i9", 4096.0, 4.0, 0.0],
            ["client", "Gateway.g1", 2048.0, 2.0, 0.0],
        ],
        sampled=640,
        evictions=3,
        cross_bytes_per_s=4096.0,
    )
    response = encode_response_frame(
        ResponseEnvelope(body=codec.serialize(snapshot))
    )

    def hexdump(label: str, frame: bytes) -> list[str]:
        lines = [f"== {label} ({len(frame)} bytes)"]
        for off in range(0, len(frame), 16):
            chunk = frame[off : off + 16]
            lines.append(f"{off:04x}  {chunk.hex(' ')}")
        return lines

    text = "\n".join(hexdump("dump_edges.request", request)
                     + hexdump("dump_edges.response", response)) + "\n"
    _assert_golden("dump_edges_frames.txt", text)

    back = codec.deserialize(codec.serialize(snapshot), EdgesSnapshot)
    assert back.rows[0][0] == "rio.StreamCursor.orders/fan"
    assert back.sampled == 640 and back.evictions == 3
    # merge_edges reads rows positionally and tolerates extra trailing
    # fields — the growth contract the golden pins.
    from rio_tpu.affinity import merge_edges

    merged = merge_edges([back.rows, [r + ["extra"] for r in back.rows]])
    assert merged[0][2] == 2 * 16384.0

def test_qos_request_frames_golden():
    """Pin the QoS-classified request-frame arities byte for byte.

    The QoS fields (tenant, priority, deadline_ms) are APPENDED wire-safe
    fields on RequestEnvelope (ISSUE 20), exactly like trace_ctx before
    them: a default-valued frame must stay byte-identical to the legacy
    4/5-element layouts (old decoders reject extra fields), and each set
    field extends the array by one trailing slot — with the trace slot
    emitted as nil to hold its position when QoS is set but the request
    is untraced. The C++ codec (native/rio_native.cc) mirrors every
    arity; tests/test_native.py pins the parity, this golden pins the
    bytes themselves.
    """
    from rio_tpu.protocol import RequestEnvelope, encode_request_frame

    cases = [
        ("legacy_4field", RequestEnvelope("Svc", "g1", "Get", b"\x01")),
        (
            "traced_5field",
            RequestEnvelope(
                "Svc", "g1", "Get", b"\x01", ("ab" * 16, "cd" * 8, True)
            ),
        ),
        (
            "tenant_6field",
            RequestEnvelope("Svc", "g1", "Get", b"\x01", tenant="bulk"),
        ),
        (
            "priority_7field",
            RequestEnvelope(
                "Svc", "g1", "Get", b"\x01", tenant="frontend", priority=2
            ),
        ),
        (
            "deadline_8field",
            RequestEnvelope(
                "Svc", "g1", "Get", b"\x01",
                tenant="frontend", priority=2, deadline_ms=1500,
            ),
        ),
        (
            "deadline_only_8field",
            RequestEnvelope("Svc", "g1", "Get", b"\x01", deadline_ms=250),
        ),
        (
            "traced_qos_8field",
            RequestEnvelope(
                "Svc", "g1", "Get", b"\x01", ("ab" * 16, "cd" * 8, True),
                tenant="frontend", priority=2, deadline_ms=1500,
            ),
        ),
    ]
    lines: list[str] = []
    for label, env in cases:
        frame = encode_request_frame(env)
        lines.append(f"== request.{label} ({len(frame)} bytes)")
        for off in range(0, len(frame), 16):
            lines.append(f"{off:04x}  {frame[off : off + 16].hex(' ')}")
    _assert_golden("qos_request_frames.txt", "\n".join(lines) + "\n")

    # The compat invariant the golden exists for: a default-QoS frame is
    # byte-identical to the pre-QoS encoding — the fields simply are not
    # on the wire.
    legacy = encode_request_frame(RequestEnvelope("Svc", "g1", "Get", b"\x01"))
    default_qos = encode_request_frame(
        RequestEnvelope(
            "Svc", "g1", "Get", b"\x01", tenant="", priority=0, deadline_ms=0
        )
    )
    assert legacy == default_qos


def test_dump_qos_frame_golden():
    """Pin the rio.Admin QoS-scrape frames byte for byte.

    DUMP_QOS is the QoS plane's operator scrape (the ``qos`` CLI speaks it
    to arbitrary-version nodes); the request envelope and the QosSnapshot
    response — including the positional per-(tenant, class) RED row shape
    [tenant, class, requests, errors, avg_ms, avg_queue_ms, sheds,
    deadline_drops] — are a compatibility contract: rows may only ever
    GROW by appending trailing fields.
    """
    from rio_tpu import codec
    from rio_tpu.admin import ADMIN_TYPE, DumpQos, QosSnapshot
    from rio_tpu.protocol import (
        RequestEnvelope,
        ResponseEnvelope,
        encode_request_frame,
        encode_response_frame,
    )

    request = encode_request_frame(
        RequestEnvelope(
            handler_type=ADMIN_TYPE,
            handler_id="10.0.0.1:5000",
            message_type="rio.DumpQos",
            payload=codec.serialize(DumpQos(limit=32)),
        )
    )
    snapshot = QosSnapshot(
        address="10.0.0.1:5000",
        enabled=True,
        running=3,
        queued=17,
        admitted=1200,
        sheds=45,
        deadline_drops=7,
        interactive_admitted=300,
        interactive_sheds=0,
        queue_depths={"fair": 15, "p2": 2},
        tenants=[
            ["bulk", "fair", 900, 12, 4.25, 18.5, 45, 3],
            ["frontend", "p2", 300, 0, 1.75, 0.4, 0, 4],
        ],
    )
    response = encode_response_frame(
        ResponseEnvelope(body=codec.serialize(snapshot))
    )

    def hexdump(label: str, frame: bytes) -> list[str]:
        lines = [f"== {label} ({len(frame)} bytes)"]
        for off in range(0, len(frame), 16):
            chunk = frame[off : off + 16]
            lines.append(f"{off:04x}  {chunk.hex(' ')}")
        return lines

    text = "\n".join(hexdump("dump_qos.request", request)
                     + hexdump("dump_qos.response", response)) + "\n"
    _assert_golden("dump_qos_frames.txt", text)

    back = codec.deserialize(codec.serialize(snapshot), QosSnapshot)
    assert back.enabled is True and back.queued == 17
    assert back.queue_depths == {"fair": 15, "p2": 2}
    assert back.tenants[0][0] == "bulk" and back.tenants[0][6] == 45
