"""Gossip failure-detector resilience under storage outages and partitions.

Satellite regressions for the fault-injection PR:

* a transient ``members()`` failure must not stop the serve loop — pings
  keep running off the last good view and the node keeps re-pushing its
  own registration (the pre-fix loop died on the first storage exception);
* an asymmetric partition (A cannot reach B, while B still reaches the
  rendezvous) must converge to a growing failure ledger WITHOUT a flapping
  activate/deactivate cycle — B's fresh heartbeat row vetoes the inactive
  verdict.
"""

import asyncio
import time

import pytest

from rio_tpu.cluster.membership_protocol.peer_to_peer import (
    PeerToPeerClusterConfig,
    PeerToPeerClusterProvider,
)
from rio_tpu.cluster.storage import LocalStorage, Member
from rio_tpu.faults import (
    FaultSchedule,
    FaultyMembershipStorage,
    StorageHealth,
    TransportFaults,
)
from rio_tpu.journal import STORAGE, Journal

A = "127.0.0.1:7101"
B = "127.0.0.1:7102"


def _fast_config(**overrides) -> PeerToPeerClusterConfig:
    base = dict(
        interval_secs=0.05,
        num_failures_threshold=1,
        interval_secs_threshold=2.0,
        ping_timeout=0.1,
    )
    base.update(overrides)
    return PeerToPeerClusterConfig(**base)


async def _wait_for(predicate, timeout: float = 5.0, what: str = "condition"):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if predicate():
            return
        await asyncio.sleep(0.01)
    raise TimeoutError(f"never reached: {what}")


@pytest.mark.asyncio
async def test_gossip_survives_transient_members_failure():
    """The satellite-1 bugfix: one members() blip must not kill the loop."""
    inner = LocalStorage()
    schedule = FaultSchedule()
    storage = FaultyMembershipStorage(inner, schedule)
    journal = Journal(capacity=64, node=A)
    provider = PeerToPeerClusterProvider(storage, _fast_config())
    provider.set_observability(journal=journal, storage_health=StorageHealth())
    # A peer that exists in the directory but listens nowhere: its pings
    # fail fast, so ledger growth proves the prober is still running.
    await inner.push(Member.from_address(B, active=True))

    task = asyncio.ensure_future(provider.serve(A))
    try:
        await _wait_for(lambda: provider.stats.ticks >= 2, what="first ticks")

        schedule.fail_all("membership.members")
        ticks_at_outage = provider.stats.ticks
        ip, port = B.rsplit(":", 1)
        failures_at_outage = len(await inner.member_failures(ip, int(port)))
        await _wait_for(
            lambda: provider.stats.degraded_ticks >= 2,
            what="degraded ticks under the outage",
        )
        # The loop is still ALIVE: ticking from the last good view, still
        # probing the dead peer (the failure ledger keeps growing).
        await _wait_for(
            lambda: provider.stats.ticks > ticks_at_outage + 1,
            what="ticks continuing through the outage",
        )
        failures_now = len(await inner.member_failures(ip, int(port)))
        assert failures_now > failures_at_outage, "prober stopped during outage"

        schedule.heal()
        push_t0 = time.time()
        await _wait_for(
            lambda: provider.stats.ticks > 0 and not provider._storage_down,
            what="recovery after heal",
        )
        # Re-push resumed: our own row's heartbeat is fresher than the heal.
        await asyncio.sleep(0.15)
        me = {m.address: m for m in await inner.members()}[A]
        assert me.active and me.last_seen >= push_t0 - 0.001

        kinds = [(ev.kind, ev.attrs.get("mode")) for ev in journal.events()]
        assert (STORAGE, "degraded") in kinds
        assert (STORAGE, "recovered") in kinds
        # One event per edge, not one per failed call.
        assert kinds.count((STORAGE, "degraded")) == 1
    finally:
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)


@pytest.mark.asyncio
async def test_gossip_registration_retries_through_boot_outage():
    """A rendezvous that is down at boot delays registration; it must not
    kill the provider before its first tick."""
    inner = LocalStorage()
    schedule = FaultSchedule()
    schedule.fail_all("membership.push")
    storage = FaultyMembershipStorage(inner, schedule)
    provider = PeerToPeerClusterProvider(storage, _fast_config())

    task = asyncio.ensure_future(provider.serve(A))
    try:
        await asyncio.sleep(0.2)
        assert await inner.members() == []  # still down: not registered
        assert not task.done(), "provider died during the boot outage"
        schedule.heal()
        await _wait_for(
            lambda: provider.stats.ticks >= 1, what="ticks after boot recovery"
        )
        assert [m.address for m in await inner.active_members()] == [A]
    finally:
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)


class _FlipCountingStorage(LocalStorage):
    """LocalStorage that counts activity flips (the flap detector)."""

    def __init__(self) -> None:
        super().__init__()
        self.deactivations: list[str] = []

    async def set_is_active(self, ip: str, port: int, active: bool) -> None:
        if not active:
            self.deactivations.append(f"{ip}:{port}")
        await super().set_is_active(ip, port, active)


async def _run_partitioned(trust: bool) -> tuple[_FlipCountingStorage, PeerToPeerClusterProvider]:
    """Drive A's prober against a one-way partitioned, heartbeat-fresh B
    for ~1s; return the storage (flip counts) and provider (stats)."""
    storage = _FlipCountingStorage()
    faults = TransportFaults()
    faults.partition(A, B)  # A cannot reach B; B reaches storage fine
    provider = PeerToPeerClusterProvider(
        storage,
        _fast_config(trust_heartbeat_freshness=trust),
        transport_faults=faults,
    )
    await storage.push(Member.from_address(B, active=True))

    async def b_heartbeat():
        while True:
            await asyncio.sleep(0.03)
            await storage.push(Member.from_address(B, active=True))

    serve = asyncio.ensure_future(provider.serve(A))
    beat = asyncio.ensure_future(b_heartbeat())
    try:
        await _wait_for(lambda: provider.stats.ticks >= 10, what="ticks")
    finally:
        for t in (serve, beat):
            t.cancel()
        await asyncio.gather(serve, beat, return_exceptions=True)
    return storage, provider


@pytest.mark.asyncio
async def test_asymmetric_partition_converges_without_flapping():
    """Satellite 3: the ledger records the one-way failure, but the fresh
    heartbeat suppresses the inactive verdict — no activate/deactivate
    churn against B's own re-push."""
    storage, provider = await _run_partitioned(trust=True)
    ip, port = B.rsplit(":", 1)
    assert len(await storage.member_failures(ip, int(port))) > 0, (
        "failure ledger did not converge on the unreachable link"
    )
    assert provider.stats.suppressed_verdicts > 0
    assert storage.deactivations == [], "anti-flap rule failed: B was deactivated"
    assert await storage.is_active(B), "heartbeat-fresh member flipped inactive"


@pytest.mark.asyncio
async def test_asymmetric_partition_flaps_without_freshness_rule():
    """The contrast run: with the veto disabled the old behavior returns —
    the prober deactivates a member that is provably still alive, and the
    member's own heartbeat re-activates it (the flap this PR removes)."""
    storage, provider = await _run_partitioned(trust=False)
    assert provider.stats.suppressed_verdicts == 0
    assert len(storage.deactivations) > 0, (
        "expected the legacy flap when trust_heartbeat_freshness=False"
    )
