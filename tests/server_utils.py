"""Multi-node-in-one-process integration harness.

Reference: ``rio-rs/tests/server_utils.rs:49-139`` — boot N real servers on
ephemeral ports inside one event loop, all sharing *aliased* in-memory
membership/placement/state fakes, race the test body against the servers and
a timeout, and tear everything down.
"""

from __future__ import annotations

import asyncio
import contextlib
from dataclasses import dataclass, field
from typing import Awaitable, Callable

from rio_tpu import (
    AppData,
    Client,
    LocalObjectPlacement,
    LocalStorage,
    Registry,
    Server,
)
from rio_tpu.cluster.membership_protocol import ClusterProvider, LocalClusterProvider
from rio_tpu.cluster.membership_protocol.peer_to_peer import (
    PeerToPeerClusterConfig,
    PeerToPeerClusterProvider,
)
from rio_tpu.object_placement import ObjectPlacement
from rio_tpu.registry import ObjectId


def fast_gossip_config() -> PeerToPeerClusterConfig:
    """Aggressive gossip for tests (reference ``server_utils.rs:25-31``)."""
    return PeerToPeerClusterConfig(
        interval_secs=0.25,
        num_failures_threshold=1,
        interval_secs_threshold=2.0,
        drop_inactive_after_secs=60.0,
        ping_timeout=0.2,
    )


@dataclass
class Cluster:
    """Everything a test body needs to poke at a running cluster."""

    servers: list[Server]
    members: LocalStorage
    placement: ObjectPlacement
    tasks: list[asyncio.Task] = field(default_factory=list)

    @property
    def addresses(self) -> list[str]:
        return [s.local_address for s in self.servers]

    def client(self, **kwargs) -> Client:
        return Client(self.members, **kwargs)

    async def is_allocated(self, type_name: str, object_id: str) -> bool:
        """Placement introspection (reference ``server_utils.rs:106-114``)."""
        return await self.placement.lookup(ObjectId(type_name, object_id)) is not None

    async def allocation_address(self, type_name: str, object_id: str) -> str | None:
        return await self.placement.lookup(ObjectId(type_name, object_id))


async def wait_for_active_members(
    members: LocalStorage, count: int, timeout: float = 10.0
) -> None:
    """Poll until ≥``count`` members are active (reference ``:119-139``)."""
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if len(await members.active_members()) >= count:
            return
        await asyncio.sleep(0.02)
    raise TimeoutError(f"never saw {count} active members")


async def run_integration_test(
    test_fn: Callable[[Cluster], Awaitable[None]],
    *,
    registry_builder: Callable[[], Registry],
    num_servers: int = 2,
    timeout: float = 30.0,
    members: LocalStorage | None = None,
    placement: ObjectPlacement | None = None,
    gossip: bool = False,
    provider_builder: Callable[[LocalStorage], ClusterProvider] | None = None,
    transport: str = "asyncio",
    server_kwargs: dict | None = None,
    app_data_builder: Callable[[], "AppData"] | None = None,
) -> None:
    members = members if members is not None else LocalStorage()
    placement = placement if placement is not None else LocalObjectPlacement()

    servers: list[Server] = []
    for _ in range(num_servers):
        if provider_builder is not None:
            provider: ClusterProvider = provider_builder(members)
        elif gossip:
            provider = PeerToPeerClusterProvider(members, fast_gossip_config())
        else:
            provider = LocalClusterProvider(members)
        extra = dict(server_kwargs or {})
        if app_data_builder is not None:
            # One AppData PER SERVER (Server.__init__ injects per-node
            # handles like AdminSender into it — sharing one instance
            # across servers would clobber them); the builder puts shared
            # fakes (e.g. an aliased ReminderStorage) into each.
            extra["app_data"] = app_data_builder()
        server = Server(
            address="127.0.0.1:0",
            registry=registry_builder(),
            cluster_provider=provider,
            object_placement_provider=placement,
            transport=transport,
            **extra,
        )
        await server.prepare()
        await server.bind()
        servers.append(server)

    cluster = Cluster(servers=servers, members=members, placement=placement)
    cluster.tasks = [asyncio.create_task(s.run()) for s in servers]
    try:
        await wait_for_active_members(members, num_servers)
        # Race the test against *all* servers exiting and the timeout
        # (reference tokio::select! over join_all(servers) vs test vs sleep,
        # server_utils.rs:92-101 — a single server exiting is a legitimate
        # event some tests trigger on purpose).
        test = asyncio.create_task(test_fn(cluster))
        # A server finishing *cleanly* (admin exit) is legitimate; a server
        # crashing with an exception fails the test immediately with that
        # exception, and all-servers-gone fails it too.
        crash: asyncio.Future = asyncio.get_event_loop().create_future()
        remaining = len(cluster.tasks)

        def on_server_done(t: asyncio.Task) -> None:
            nonlocal remaining
            remaining -= 1
            if crash.done():
                return
            exc = None if t.cancelled() else t.exception()
            if exc is not None:
                crash.set_exception(exc)
            elif remaining == 0:
                crash.set_exception(
                    AssertionError("every server exited before the test completed")
                )

        for t in cluster.tasks:
            t.add_done_callback(on_server_done)
        done, _ = await asyncio.wait(
            [test, crash], timeout=timeout, return_when=asyncio.FIRST_COMPLETED
        )
        if not done:
            test.cancel()
            crash.cancel()
            raise TimeoutError(f"integration test timed out after {timeout}s")
        if test in done:
            # Retrieve (and surface) a crash that completed in the same
            # wakeup; cancel() on an already-failed future is a no-op and
            # would leave its exception unretrieved.
            if crash.done() and not crash.cancelled():
                crash.result()
            else:
                crash.cancel()
            test.result()  # re-raise test failures
        else:
            test.cancel()
            crash.result()  # raises the server's exception
    finally:
        for t in cluster.tasks:
            t.cancel()
        await asyncio.gather(*cluster.tasks, return_exceptions=True)
        with contextlib.suppress(Exception):
            for s in servers:
                s._listener and s._listener.close()
