"""JaxObjectPlacement: trait parity + batched/device behaviors.

Trait semantics mirror the reference backend matrix
(``rio-rs/tests/object_placement_backend.rs``); the batched/rebalance paths
are rio-tpu additions.
"""

import numpy as np
import pytest

from rio_tpu import ObjectId, ObjectPlacementItem
from rio_tpu.errors import NoSchedulableCapacity
from rio_tpu.object_placement.jax_placement import JaxObjectPlacement


def _provider(nodes=4, **kw):
    p = JaxObjectPlacement(node_axis_size=16, **kw)
    for i in range(nodes):
        p.register_node(f"10.0.0.{i}:5000")
    return p


async def test_assign_batch_empty_cluster_raises_no_schedulable_capacity():
    """No registered (or no live) nodes is a documented, typed error — not
    the bare ValueError the solver guts used to leak — and it still
    satisfies ``except ValueError`` for callers written against that."""
    p = JaxObjectPlacement(node_axis_size=16)
    with pytest.raises(NoSchedulableCapacity, match="register_node"):
        await p.assign_batch([ObjectId("Game", "g0")])
    assert issubclass(NoSchedulableCapacity, ValueError)
    # (All-registered-but-dead is NOT this error: the all-dead blip still
    # seats on real nodes — see ``_least_loaded_spread``.)


async def test_trait_parity_update_lookup_remove():
    p = _provider()
    oid = ObjectId("MetricAggregator", "instance-1")
    assert await p.lookup(oid) is None
    await p.update(ObjectPlacementItem(oid, "10.0.0.1:5000"))
    assert await p.lookup(oid) == "10.0.0.1:5000"
    await p.update(ObjectPlacementItem(oid, "10.0.0.2:5000"))  # upsert
    assert await p.lookup(oid) == "10.0.0.2:5000"
    await p.remove(oid)
    assert await p.lookup(oid) is None


async def test_trait_parity_clean_server():
    p = _provider()
    a = ObjectId("T", "a")
    b = ObjectId("T", "b")
    await p.update(ObjectPlacementItem(a, "10.0.0.1:5000"))
    await p.update(ObjectPlacementItem(b, "10.0.0.2:5000"))
    await p.clean_server("10.0.0.1:5000")
    assert await p.lookup(a) is None
    assert await p.lookup(b) == "10.0.0.2:5000"


async def test_assign_batch_spreads_and_is_sticky():
    p = _provider(nodes=4)
    oids = [ObjectId("Game", str(i)) for i in range(400)]
    addrs = await p.assign_batch(oids)
    counts = {}
    for a in addrs:
        counts[a] = counts.get(a, 0) + 1
    assert len(counts) == 4
    assert max(counts.values()) <= 2 * 100
    # Re-assigning returns identical seats (no churn).
    again = await p.assign_batch(oids)
    assert addrs == again
    assert p.count() == 400


async def test_assign_batch_avoids_dead_nodes():
    p = _provider(nodes=4)

    class M:
        def __init__(self, addr, active):
            self._addr, self.active = addr, active

        def address(self):
            return self._addr

    members = [M(f"10.0.0.{i}:5000", i != 2) for i in range(4)]
    p.sync_members(members)
    addrs = await p.assign_batch([ObjectId("T", str(i)) for i in range(100)])
    assert "10.0.0.2:5000" not in addrs


async def test_rebalance_sinkhorn_levels_skew():
    p = _provider(nodes=4)
    # Pile everything onto one node, then re-solve.
    for i in range(200):
        await p.update(ObjectPlacementItem(ObjectId("T", str(i)), "10.0.0.0:5000"))
    moved = await p.rebalance(mode="sinkhorn")
    assert moved > 0
    addrs = await p.lookup_batch([ObjectId("T", str(i)) for i in range(200)])
    counts = np.unique(addrs, return_counts=True)[1]
    assert counts.max() <= 2 * 200 / 4
    assert p.stats.n_objects == 200
    assert p.stats.solve_ms > 0


async def test_rebalance_greedy_mode():
    p = _provider(nodes=4)
    for i in range(128):
        await p.update(ObjectPlacementItem(ObjectId("T", str(i)), "10.0.0.3:5000"))
    moved = await p.rebalance(mode="greedy")
    assert moved > 0
    addrs = await p.lookup_batch([ObjectId("T", str(i)) for i in range(128)])
    counts = np.unique(addrs, return_counts=True)[1]
    assert counts.max() <= 2 * 128 / 4


async def test_incremental_after_rebalance_uses_potentials():
    p = _provider(nodes=4)
    await p.assign_batch([ObjectId("T", str(i)) for i in range(64)])
    await p.rebalance(mode="sinkhorn")
    assert p._g is not None
    # New arrivals take the cached-potentials fast path.
    addrs = await p.assign_batch([ObjectId("U", str(i)) for i in range(32)])
    assert all(a.startswith("10.0.0.") for a in addrs)


async def test_potentials_survive_no_op_and_additive_churn():
    """``_g`` is versioned by the schedulable-node fingerprint, not nulled
    on every sync_members: a sync that changes nothing — and even a NEW
    node joining — keeps the cached potentials (the newcomer's entry is
    -inf, so the warm assign path conservatively never seats there until
    the next solve learns it)."""

    class M:
        def __init__(self, address, active=True):
            self.address = address
            self.active = active

    p = _provider(nodes=4)
    await p.assign_batch([ObjectId("T", str(i)) for i in range(64)])
    await p.rebalance(mode="sinkhorn")
    g = p._g
    assert g is not None
    members = [M(f"10.0.0.{i}:5000") for i in range(4)]
    p.sync_members(members)  # no liveness change
    assert p._g is g
    p.sync_members(members + [M("10.0.0.9:5000")])  # additive join
    assert p._g is g


async def test_dead_node_still_invalidates_potentials():
    """Regression guard for the fingerprint versioning: a node LEAVING the
    schedulable set (solved-over potentials now lie about live capacity)
    must still drop ``_g`` — both via sync_members and via cordon."""

    class M:
        def __init__(self, address, active=True):
            self.address = address
            self.active = active

    p = _provider(nodes=4)
    await p.assign_batch([ObjectId("T", str(i)) for i in range(64)])
    await p.rebalance(mode="sinkhorn")
    assert p._g is not None
    p.sync_members(
        [M(f"10.0.0.{i}:5000", active=(i != 2)) for i in range(4)]
    )
    assert p._g is None
    await p.rebalance(mode="sinkhorn")
    assert p._g is not None
    p.cordon("10.0.0.1:5000")
    assert p._g is None


async def test_node_axis_grows():
    p = JaxObjectPlacement(node_axis_size=2)
    for i in range(5):
        p.register_node(f"10.0.1.{i}:5000")
    addrs = await p.assign_batch([ObjectId("T", str(i)) for i in range(50)])
    assert len(set(addrs)) == 5


async def test_sync_members_with_real_member_objects():
    # Regression: Member.address is a property (str), not a method.
    from rio_tpu.cluster.storage import Member

    p = _provider(nodes=0)
    members = [Member.from_address(f"10.1.0.{i}:5000", active=(i != 1)) for i in range(3)]
    p.sync_members(members)
    assert set(p._nodes) == {f"10.1.0.{i}:5000" for i in range(3)}
    assert p._nodes["10.1.0.1:5000"].alive is False
    addrs = await p.assign_batch([ObjectId("T", str(i)) for i in range(40)])
    assert "10.1.0.1:5000" not in addrs
    assert set(addrs) == {"10.1.0.0:5000", "10.1.0.2:5000"}


async def test_rebalance_hierarchical_mode():
    """Two-level OT mode: valid, live-only, reasonably balanced placements."""
    placement = JaxObjectPlacement(mode="hierarchical", n_iters=15)
    for i in range(16):
        placement.register_node(f"10.1.0.{i}:70")
    ids = [ObjectId("H", str(i)) for i in range(800)]
    await placement.assign_batch(ids)
    await placement.clean_server("10.1.0.3:70")
    orphans = [i for i in ids if await placement.lookup(i) is None]
    await placement.assign_batch(orphans)
    moved = await placement.rebalance()
    assert moved >= 0
    counts: dict[str, int] = {}
    for oid in ids:
        addr = await placement.lookup(oid)
        assert addr is not None and addr != "10.1.0.3:70"
        counts[addr] = counts.get(addr, 0) + 1
    fair = len(ids) / 15
    assert max(counts.values()) < 2.5 * fair
    assert placement.stats.mode == "hierarchical"


async def test_full_rebalance_moves_only_displaced_share():
    """Churn-aware re-solve: killing 10% of nodes must move ~10% of objects.

    The stay-put discount (``move_cost``) makes the full ``rebalance()``
    prefer each object's current seat; only capacity pressure from the dead
    nodes forces moves (BASELINE.md row 4 — the reference re-places on
    lookup-miss only, so its analog never reshuffles healthy placements
    either; a TPU re-solve must not regress that).
    """
    n_nodes, n_objects = 20, 2000
    p = JaxObjectPlacement(mode="sinkhorn")
    for i in range(n_nodes):
        p.register_node(f"10.0.0.{i}:50")
    ids = [ObjectId("T", str(i)) for i in range(n_objects)]
    await p.assign_batch(ids)
    await p.rebalance()
    before = {str(i): await p.lookup(i) for i in ids}

    # 2 of 20 nodes die via gossip (placements stay, liveness flips).
    class M:
        def __init__(self, addr, active):
            self.address, self.active = addr, active

    p.sync_members(
        [M(f"10.0.0.{i}:50", active=i >= 2) for i in range(n_nodes)]
    )
    displaced = sum(
        1 for i in ids if before[str(i)] in (f"10.0.0.{j}:50" for j in range(2))
    )
    assert displaced > 0

    moved = await p.rebalance()
    assert p.stats.moved == moved
    # Moves are bounded by the displaced share plus slack for capacity
    # re-leveling (18 nodes absorbing the orphans shift fair shares a bit).
    assert moved <= int(1.5 * displaced) + n_nodes, (moved, displaced)
    # Every object lives on a live node, load stays capacity-sane.
    counts: dict[str, int] = {}
    for i in ids:
        addr = await p.lookup(i)
        assert addr is not None and not addr.startswith(("10.0.0.0:", "10.0.0.1:"))
        counts[addr] = counts.get(addr, 0) + 1
    fair = n_objects / (n_nodes - 2)
    assert max(counts.values()) < 2.0 * fair


async def test_second_rebalance_is_stationary():
    """With no churn between solves, a re-solve must move (almost) nothing."""
    p = _provider(nodes=8)
    ids = [ObjectId("T", str(i)) for i in range(800)]
    await p.assign_batch(ids)
    await p.rebalance()
    moved = await p.rebalance()
    assert moved <= len(ids) // 50, moved  # <=2% drift, not a reshuffle


@pytest.mark.slow
async def test_directory_scale_budgets():
    """1M-entry host directory: mutation paths must stay off O(total) scans.

    Budgets are generous (CI machines vary) but catch the O(N)-per-op
    regressions: clean_server via the per-node index is O(objects-on-node),
    lookups stay O(1).
    """
    import time

    p = JaxObjectPlacement(node_axis_size=64)
    for i in range(64):
        p.register_node(f"10.0.{i // 256}.{i % 256}:50")

    n = 1_000_000
    t0 = time.perf_counter()
    # Bulk insert through the same internal the trait paths use.
    for i in range(n):
        p._set_placement(f"T.{i}", i & 63)
    insert_s = time.perf_counter() - t0
    assert p.count() == n

    t0 = time.perf_counter()
    for i in range(0, n, 1000):
        assert (await p.lookup(ObjectId("T", str(i)))) is not None
    lookup_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    await p.clean_server("10.0.0.7:50")
    clean_s = time.perf_counter() - t0
    assert p.count() == n - n // 64

    # Re-homing the orphans against cached-potential-free greedy path.
    assert insert_s < 30.0, insert_s
    assert lookup_s < 1.0, lookup_s
    assert clean_s < 2.0, clean_s


async def test_hierarchical_affinity_tracker_steers_placement():
    """Real locality signal through the feature hooks must steer the solve.

    AffinityTracker turns observed traffic into features; after observing
    each object on a "home" node, a hierarchical re-solve should send the
    vast majority home (vs ~1/M for the hashed-identity default) while the
    capacity marginals keep load balanced. This is the semantic-affinity
    hook VERDICT flagged: the 2-level OT now optimizes something real.
    """
    from rio_tpu.object_placement.jax_placement import AffinityTracker

    tracker = AffinityTracker(dim=32)
    p = JaxObjectPlacement(
        mode="hierarchical",
        n_iters=20,
        obj_features=tracker.obj_features,
        node_features=tracker.node_features,
    )
    nodes = [f"10.0.0.{i}:50" for i in range(16)]
    for a in nodes:
        p.register_node(a)
    ids = [ObjectId("T", str(i)) for i in range(320)]
    home = {str(ids[i]): nodes[i % 16] for i in range(320)}
    await p.assign_batch(ids)
    # weight=1.0 keeps alpha below 1 so the real EMA blend + cold-start
    # seeding + renormalization paths are exercised (two rounds converge
    # the feature toward the home embedding without pinning it outright).
    for _ in range(2):
        for k, a in home.items():
            tracker.observe(k, a, weight=1.0)
    await p.rebalance()

    hit = 0
    counts: dict[str, int] = {}
    for i in ids:
        a = await p.lookup(i)
        counts[a] = counts.get(a, 0) + 1
        if a == home[str(i)]:
            hit += 1
    fair = len(ids) / len(nodes)
    assert hit >= 0.75 * len(ids), hit  # measured ~92%; hashed default ~9%
    assert max(counts.values()) <= 2.0 * fair, counts


async def test_rebalance_exact_capacity_with_minimal_churn():
    """Flat-mode rebalance lands EXACT integer quotas at zero extra churn.

    After killing 2 of 20 nodes: every displaced object moves (they must),
    nothing else does (stay-put preference in the quota repair evicts
    movers first), and the survivors' loads match largest-remainder quotas
    exactly (111/112 for 2000 over 18).
    """
    import numpy as np

    n_nodes, n_objects = 20, 2000
    p = JaxObjectPlacement(mode="sinkhorn")
    for i in range(n_nodes):
        p.register_node(f"10.0.0.{i}:50")
    ids = [ObjectId("T", str(i)) for i in range(n_objects)]
    await p.assign_batch(ids)
    await p.rebalance()
    before = {str(i): await p.lookup(i) for i in ids}

    class M:
        def __init__(self, addr, active):
            self.address, self.active = addr, active

    p.sync_members([M(f"10.0.0.{i}:50", active=i >= 2) for i in range(n_nodes)])
    dead = {f"10.0.0.{j}:50" for j in range(2)}
    displaced = sum(1 for v in before.values() if v in dead)
    moved = await p.rebalance()
    assert moved == displaced, (moved, displaced)

    after = [await p.lookup(i) for i in ids]
    assert not any(a in dead for a in after)
    loads = np.bincount(
        [int(a.rsplit(":", 1)[0].rsplit(".", 1)[1]) for a in after],
        minlength=n_nodes,
    )
    live = loads[2:]
    assert int(live.max()) - int(live.min()) <= 1  # exact integer quotas


async def test_flat_rebalance_uses_collapsed_solve():
    """Flat modes collapse to the (M x M) class problem — N drops out.

    The class solve + move-minimal application must move EXACTLY the
    displaced share (zero off-diagonal churn at the sharpened class eps)
    and record the collapsed mode; solve time must not scale with N on
    the device (the N-sized work is one host pass + the quota repair).
    """
    import numpy as np

    m, n = 64, 20_000
    p = JaxObjectPlacement(mode="sinkhorn")
    for i in range(m):
        p.register_node(f"10.0.{i // 16}.{i % 16}:50")
    rng = np.random.default_rng(3)
    seats = rng.integers(0, m, n)
    for i, idx in enumerate(seats):
        p._set_placement(f"T.{i}", int(idx))
    p._recount_loads()

    class M:
        def __init__(self, addr, active):
            self.address, self.active = addr, active

    members = [
        M(f"10.0.{i // 16}.{i % 16}:50", active=i >= 6) for i in range(m)
    ]
    p.sync_members(members)
    displaced = int((seats < 6).sum())
    moved = await p.rebalance()
    assert p.stats.mode == "sinkhorn+collapsed"
    # Zero off-diagonal churn from the solve itself; per-row quota
    # rounding can drift columns by +-1 each, so the repair may move up
    # to ~M extra objects — bounded by the NODE count, never a fraction
    # of N (at 1M x 1024 measured extra was exactly 0).
    assert displaced <= moved <= displaced + m, (moved, displaced)
    loads = np.bincount(list(p._placements.values()), minlength=p._node_axis)
    assert loads[:6].sum() == 0
    live = loads[6:m]
    assert int(live.max()) - int(live.min()) <= 1


def test_apply_class_quotas_unit():
    """Quota expansion keeps quota[k,k] objects seated, spills the rest."""
    import numpy as np

    from rio_tpu.object_placement.jax_placement import _apply_class_quotas

    quotas = np.array(
        [
            [2, 1, 0],  # class 0: keep 2, send 1 to node 1
            [0, 3, 0],  # class 1: all stay
            [1, 0, 1],  # class 2: one to node 0, one stays
        ],
        np.int32,
    )
    cur = np.array([0, 0, 0, 1, 1, 1, 2, 2], np.int32)
    out = _apply_class_quotas(quotas, cur)
    assert np.bincount(out, minlength=3).tolist() == [3, 4, 1]
    # stay-put priority: exactly quota[k,k] of each class unchanged
    for k in range(3):
        stayed = int(((cur == k) & (out == k)).sum())
        assert stayed == quotas[k, k]


def test_expand_class_quotas_matches_host_apply():
    """Device quota expansion is byte-identical to the host expansion.

    The collapsed rebalance now runs expansion on device
    (``ops.structured.expand_class_quotas``); the host
    ``_apply_class_quotas`` stays as the semantic reference. Covers
    padding (bucket > n), empty classes, and skewed quota rows.
    """
    import jax.numpy as jnp
    import numpy as np

    from rio_tpu.object_placement.jax_placement import _apply_class_quotas
    from rio_tpu.ops.structured import expand_class_quotas

    rng = np.random.default_rng(7)
    for m, n in ((3, 8), (17, 900), (64, 4000)):
        cur = rng.integers(0, m, n).astype(np.int32)
        cur[: n // 5] = 0  # ensure class 0 is populated (padding shares it)
        counts = np.bincount(cur, minlength=m)
        quotas = np.zeros((m, m), np.int32)
        for k in range(m):
            if counts[k]:
                quotas[k] = rng.multinomial(counts[k], np.ones(m) / m)
        host = _apply_class_quotas(quotas, cur)
        bucket = 1
        while bucket < n:
            bucket *= 2
        cur_pad = np.zeros(bucket, np.int32)
        cur_pad[:n] = cur
        dev = np.asarray(
            expand_class_quotas(jnp.asarray(quotas), jnp.asarray(cur_pad))
        )[:n]
        assert (host == dev).all(), (m, n, np.nonzero(host != dev)[0][:5])


def test_provider_construction_initializes_no_backend():
    """Constructing a provider must NEVER initialize a jax backend.

    Regression for the r3 bench freeze: mode="auto" once resolved via
    jax.default_backend() in __init__, and against a wedged TPU relay
    that init hangs indefinitely — construction (e.g. inside a Server
    bootstrap or the bench orchestrator) must stay backend-free; the
    first SOLVE initializes the backend instead.
    """
    import subprocess
    import sys as _sys

    code = (
        "from rio_tpu.object_placement.jax_placement import JaxObjectPlacement\n"
        "p = JaxObjectPlacement()\n"
        "p.register_node('10.0.0.1:1')\n"
        "from jax._src import xla_bridge as xb\n"
        "assert not xb._backends, f'backend initialized: {list(xb._backends)}'\n"
        "print('CLEAN')\n"
    )
    proc = subprocess.run(
        [_sys.executable, "-c", code],
        capture_output=True,
        env={"PYTHONPATH": ".", "JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin"},
        cwd="/root/repo",
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr.decode()
    assert b"CLEAN" in proc.stdout


def test_guard_sentinel_spill_reseats_on_live_capacity():
    """fp32 largest-remainder drift can park a real object on the padding
    sentinel column (r4: bucket=2^24 == the fp32 integer boundary); the
    guard must reseat exactly those rows on the best live node and leave
    everything else untouched."""
    import jax.numpy as jnp

    from rio_tpu.object_placement.jax_placement import _guard_sentinel_spill

    m_axis = 4
    #             real rows --------------  padding
    repaired = jnp.asarray([0, m_axis, 2, 1, m_axis, m_axis], jnp.int32)
    real = jnp.asarray([True, True, True, True, False, False])
    cap_alive = jnp.asarray([1.0, 0.0, 2.0, 1.0], jnp.float32)  # node 1 dead
    out = _guard_sentinel_spill(repaired, real, m_axis, cap_alive)
    # Row 1 (real, spilled) reseats on node 2 (max live capacity); padding
    # rows keep the sentinel; everyone else is untouched.
    assert out.tolist() == [0, 2, 2, 1, m_axis, m_axis]


async def test_assign_batch_concurrent_with_membership_churn():
    """The chunked off-loop solve must tolerate loop-side membership
    mutations between/during chunks: sync_members and register_node run
    lock-free on the event loop while the solver thread reads only
    snapshots (r4 race fix). The batch spans multiple chunks; memberships
    flip while it runs; every object must land on a known node."""
    import asyncio

    placement = JaxObjectPlacement(mode="greedy")
    base = [f"10.1.0.{i}:70" for i in range(8)]
    placement.sync_members(base)

    churn_done = asyncio.Event()

    async def churner():
        extra = 8
        while not churn_done.is_set():
            # Flip a member out and in, and grow the node set (which can
            # double the node axis mid-batch).
            placement.sync_members(base[1:])
            await asyncio.sleep(0)
            placement.sync_members(base + [f"10.1.1.{extra}:70"])
            extra += 1
            await asyncio.sleep(0)

    # Shrink the chunk so the batch needs several solve round trips.
    old_chunk = JaxObjectPlacement._MAX_PLACE_CHUNK
    JaxObjectPlacement._MAX_PLACE_CHUNK = 1024
    try:
        task = asyncio.create_task(churner())
        ids = [ObjectId("Race", str(i)) for i in range(6000)]
        where = await placement.assign_batch(ids)
    finally:
        churn_done.set()
        await task
        JaxObjectPlacement._MAX_PLACE_CHUNK = old_chunk
    assert len(where) == len(ids)
    known = set(placement._node_order)
    assert all(w in known for w in where)
    # The directory answers for every object afterwards.
    looked = await placement.lookup_batch(ids)
    assert all(w is not None for w in looked)


async def test_assign_batch_releases_lock_between_chunks():
    """ADVICE r4: a huge batch must not hold the provider lock for its
    whole runtime. A locked mutator (remove of a chunk-0 key) queues on the
    lock WHILE chunk 0 is still held, so FIFO fairness serves it in the
    between-chunk gap — it must complete while the batch is still running
    (the old whole-batch hold blocked it until the end), and the batch's
    final resolution pass must re-place the removed straggler."""
    import asyncio

    placement = JaxObjectPlacement(mode="greedy")
    placement.sync_members([f"10.5.0.{i}:70" for i in range(4)])

    chunk0_done = asyncio.Event()
    batch_done = False
    removed_while_batch_ran = None
    orig = JaxObjectPlacement._place_chunk_locked

    async def chunk_and_signal(self, chunk):
        await orig(self, chunk)
        if not chunk0_done.is_set():
            chunk0_done.set()
            # Still holding the lock: yield so the mutator wakes and QUEUES
            # its lock request behind us — FIFO then guarantees it runs in
            # the gap before chunk 1, not after the whole batch.
            for _ in range(5):
                await asyncio.sleep(0)

    ids = [ObjectId("Big", str(i)) for i in range(4000)]
    straggler = ids[3]  # placed in chunk 0

    async def mutator():
        nonlocal removed_while_batch_ran
        await chunk0_done.wait()
        await placement.remove(straggler)
        removed_while_batch_ran = not batch_done

    old_chunk = JaxObjectPlacement._MAX_PLACE_CHUNK
    JaxObjectPlacement._MAX_PLACE_CHUNK = 512
    JaxObjectPlacement._place_chunk_locked = chunk_and_signal
    try:
        task = asyncio.create_task(mutator())
        where = await placement.assign_batch(ids)
        batch_done = True
        await asyncio.wait_for(task, 30)
    finally:
        JaxObjectPlacement._MAX_PLACE_CHUNK = old_chunk
        JaxObjectPlacement._place_chunk_locked = orig
    # The remove interleaved mid-batch (lock released between chunks)...
    assert removed_while_batch_ran is True
    # ...and the final resolution re-placed it: every key resolves.
    assert len(where) == len(ids)
    known = set(placement._node_order)
    assert all(w in known for w in where)
    looked = await placement.lookup_batch(ids)
    assert all(w is not None for w in looked)


async def test_cordon_drains_node_gracefully():
    """kubectl-cordon analog: a cordoned node takes no NEW seats, a
    rebalance re-seats exactly ~its population (not a global reshuffle),
    and uncordon makes it schedulable again."""
    import asyncio  # noqa: F401  (parity with sibling tests)

    p = JaxObjectPlacement(mode="greedy", move_cost=0.5)
    nodes = [f"10.6.0.{i}:70" for i in range(4)]
    p.sync_members(nodes)
    ids = [ObjectId("D", str(i)) for i in range(400)]
    await p.assign_batch(ids)
    victim = await p.lookup(ids[0])
    on_victim = sum(1 for w in await p.lookup_batch(ids) if w == victim)
    assert on_victim > 0

    p.cordon(victim)
    assert p.cordoned == {victim}
    # New allocations avoid it...
    where_new = await p.assign_batch([ObjectId("D", f"n{i}") for i in range(60)])
    assert victim not in where_new
    # ...its existing rows still resolve (it keeps serving)...
    assert await p.lookup(ids[0]) == victim
    # ...and a rebalance drains it, moving ~only its population.
    moved = await p.rebalance()
    where = await p.lookup_batch(ids)
    assert victim not in where
    assert moved <= on_victim + 460 // 3, (moved, on_victim)

    p.uncordon(victim)
    refill = await p.assign_batch([ObjectId("D", f"m{i}") for i in range(200)])
    assert victim in refill  # the drained node is schedulable (and emptiest)


async def test_cordon_refuses_last_schedulable_node():
    p = JaxObjectPlacement(mode="greedy")
    p.sync_members(["10.6.1.0:70", "10.6.1.1:70"])
    p.cordon("10.6.1.0:70")
    import pytest

    with pytest.raises(RuntimeError):
        p.cordon("10.6.1.1:70")
    with pytest.raises(KeyError):
        p.cordon("10.6.9.9:70")
    p.uncordon("10.6.1.0:70")
    assert p.cordoned == set()


async def test_hierarchical_rebalance_compiles_are_bucket_bounded():
    """r5 endurance regression: a steadily-allocating cluster must NOT
    compile a fresh hierarchical executable per rebalance (the jit cache
    retained ~25 MB per new directory size — ~1 GB/hour). The object axis
    is padded to power-of-two buckets, so rebalances at many different
    sizes within one bucket reuse ONE trace."""
    from rio_tpu.parallel.hierarchical import hierarchical_assign

    if not hasattr(hierarchical_assign, "_cache_size"):
        import pytest

        pytest.skip("jax jit cache probe (_cache_size) unavailable")
    p = JaxObjectPlacement(mode="hierarchical")
    p.sync_members([f"10.11.0.{i}:70" for i in range(3)])
    hierarchical_assign.clear_cache()
    n = 0
    for step in range(6):
        ids = [ObjectId("B", str(n + i)) for i in range(37)]  # 37: new n each step
        n += 37
        await p.assign_batch(ids)
        await p.rebalance(delta=False)  # pin the FULL path's compile bound
    # 6 different directory sizes, all inside the 256-bucket: one trace.
    assert hierarchical_assign._cache_size() == 1, hierarchical_assign._cache_size()
    # Crossing the bucket boundary adds exactly one more.
    ids = [ObjectId("B", str(n + i)) for i in range(120)]
    await p.assign_batch(ids)
    await p.rebalance(delta=False)
    assert hierarchical_assign._cache_size() == 2, hierarchical_assign._cache_size()


async def test_solve_stats_history_records_prior_solves():
    placement = JaxObjectPlacement(mode="greedy")
    placement.sync_members([f"10.2.0.{i}:80" for i in range(4)])
    ids = [ObjectId("Hist", str(i)) for i in range(200)]
    await placement.assign_batch(ids)
    await placement.rebalance()
    first_epoch = placement.stats.epoch
    assert placement.stats.history == []  # nothing completed before it
    await placement.rebalance()
    hist = placement.stats.history
    assert [h.epoch for h in hist] == [first_epoch]
    assert hist[0].history == []  # entries are flat, never nested
    assert placement.stats.epoch > first_epoch


async def test_hierarchical_rebalance_chunks_above_threshold(monkeypatch):
    """Above _HIER_CHUNK_ROWS the single-chip hierarchical solve must route
    through chunked_hierarchical_assign (TPU compile is superlinear in the
    flat row count; the chunked body compiles once at the chunk shape) and
    still produce a valid, balanced directory."""
    from rio_tpu.object_placement import jax_placement as jp_mod
    from rio_tpu.parallel import hierarchical as hier_mod

    monkeypatch.setattr(jp_mod, "_HIER_CHUNK_ROWS", 512)
    calls = {"n_chunks": None}
    # The placement routes through the timed host-loop twin by default
    # (RIO_TPU_CHUNK_TIMING=1) and the lax.map form when it's off; spy on
    # both so the test pins the routing, not the timing flavor.
    for name in ("chunked_hierarchical_assign", "chunked_hierarchical_assign_timed"):
        real = getattr(hier_mod, name)

        def spy(*args, _real=real, **kw):
            calls["n_chunks"] = kw.get("n_chunks")
            return _real(*args, **kw)

        monkeypatch.setattr(hier_mod, name, spy)

    p = JaxObjectPlacement(mode="hierarchical", n_iters=10)
    members = [f"10.31.0.{i}:70" for i in range(6)]
    p.sync_members(members)
    ids = [ObjectId("Chunky", str(i)) for i in range(1200)]  # bucket 2048 -> 4 chunks
    await p.assign_batch(ids)
    await p.rebalance()
    assert calls["n_chunks"] == 4
    # Directory still complete, every seat on a live member, loads balanced.
    addrs = [await p.lookup(i) for i in ids]
    assert all(a in members for a in addrs)
    from collections import Counter

    loads = Counter(addrs)
    assert max(loads.values()) <= 2.0 * (1200 / 6)


async def test_flat_rebalance_routes_to_hierarchical_at_scale(monkeypatch):
    """Flat OT modes above _FLAT_REBALANCE_MAX_ROWS must re-solve through
    the two-level pipeline (the flat collapsed expansion is
    compile-infeasible on the TPU backend at 10M-row shapes) and record
    what actually ran in SolveStats.mode."""
    from rio_tpu.object_placement import jax_placement as jp_mod

    monkeypatch.setattr(jp_mod, "_FLAT_REBALANCE_MAX_ROWS", 256)
    p = JaxObjectPlacement(mode="sinkhorn", n_iters=10)
    members = [f"10.32.0.{i}:70" for i in range(5)]
    p.sync_members(members)
    ids = [ObjectId("Big", str(i)) for i in range(700)]  # bucket 1024 > 256
    await p.assign_batch(ids)
    moved = await p.rebalance()
    assert p.stats.mode == "sinkhorn+hier_at_scale"
    assert moved >= 0
    addrs = [await p.lookup(i) for i in ids]
    assert all(a in members for a in addrs)
    from collections import Counter

    loads = Counter(addrs)
    assert max(loads.values()) <= 2.0 * (700 / 5)
    # Below the threshold the collapsed fast path still runs.
    monkeypatch.setattr(jp_mod, "_FLAT_REBALANCE_MAX_ROWS", 1 << 20)
    await p.rebalance(delta=False)
    assert p.stats.mode == "sinkhorn+collapsed"


async def test_routed_hier_rebalance_honors_move_cost(monkeypatch):
    """Review regression: a flat-mode rebalance routed through the
    hierarchical solve at scale must keep stay-put semantics. The pull of
    move_cost toward the current seat's embedding is the feature-space
    analog of the flat path's stay-put diagonal: re-solving an
    already-seated directory must move almost nothing (measured 12 vs 631
    unsticky), and a node death must move ~only the displaced share."""
    from rio_tpu.object_placement import jax_placement as jp_mod

    monkeypatch.setattr(jp_mod, "_FLAT_REBALANCE_MAX_ROWS", 256)
    members = [f"10.40.0.{i}:70" for i in range(8)]
    ids = [ObjectId("S", str(i)) for i in range(700)]

    async def settle_and_kill(move_cost):
        p = JaxObjectPlacement(mode="sinkhorn", n_iters=10, move_cost=move_cost)
        p.sync_members(members)
        await p.assign_batch(ids)
        settle = await p.rebalance()
        assert p.stats.mode == "sinkhorn+hier_at_scale"
        p.sync_members(members[:-1])
        after_kill = await p.rebalance()
        addrs = [await p.lookup(i) for i in ids]
        assert all(a in members[:-1] for a in addrs)  # dead node vacated
        return settle, after_kill

    settle_free, _ = await settle_and_kill(0.0)
    settle_sticky, after_kill = await settle_and_kill(1.0)
    displaced = 700 / 8
    # The absolute sticky count is jax-version sensitive (measured 12 on
    # jax>=0.6, 63 on 0.4.37 — Sinkhorn numerics shift the marginal group
    # boundaries); the contract is the RATIO: sticky must be a small
    # fraction of the population and far below the unsticky solve (~600).
    assert settle_sticky <= 100, settle_sticky           # measured 12-63
    assert settle_free >= 5 * settle_sticky + 100        # measured 609-631
    assert after_kill <= 2.0 * displaced, after_kill     # measured 90-93


async def test_mesh_flat_rebalance_routes_by_per_shard_rows(monkeypatch):
    """Review regression: the compile-feasibility guard keys on PER-SHARD
    rows — a mesh-sharded flat solve whose shards exceed the proven bound
    must route to the sharded hierarchical branch, and one whose shards
    fit must keep the dense sharded path."""
    from rio_tpu.object_placement import jax_placement as jp_mod
    from rio_tpu.parallel import make_mesh

    mesh = make_mesh()  # 8 virtual CPU devices (conftest)
    n_dev = int(mesh.devices.size)
    members = [f"10.41.0.{i}:70" for i in range(6)]
    ids = [ObjectId("MeshBig", str(i)) for i in range(700)]  # bucket 1024

    async def run(threshold):
        p = JaxObjectPlacement(mode="sinkhorn", n_iters=10, mesh=mesh)
        p.sync_members(members)
        await p.assign_batch(ids)
        await p.rebalance()
        addrs = [await p.lookup(i) for i in ids]
        assert all(a in members for a in addrs)
        return p.stats.mode

    # bucket/n_dev = 128 per shard: > 64 routes, > 1024 keeps dense.
    monkeypatch.setattr(jp_mod, "_FLAT_REBALANCE_MAX_ROWS", 64)
    assert await run(64) == "sinkhorn+hier_at_scale"
    monkeypatch.setattr(jp_mod, "_FLAT_REBALANCE_MAX_ROWS", 1024)
    assert await run(1024) == "sinkhorn"


async def test_assign_with_every_node_dead_still_seats_on_real_nodes():
    """A clean_server storm can mark EVERY node dead between gossip ticks
    (80-wave soak, wave 46): the waterfill's width vector collapses and,
    unguarded, searchsorted clipped rows onto padded-axis slots — a pad
    index in the directory then blew up every _node_order[idx] resolution
    (IndexError in the persistence mark was the observed symptom). The
    directory must still seat such objects on REAL nodes (reference
    semantics: placement rows outlive their owner, service.rs:213-238);
    the next liveness change re-seats them."""
    p = JaxObjectPlacement(mode="greedy", move_cost=0.5)
    members = [f"10.9.0.{i}:70" for i in range(6)]
    p.sync_members(members)
    ids = [ObjectId("Dead", str(i)) for i in range(40)]
    await p.assign_batch(ids[:10])
    for a in members:
        await p.clean_server(a)  # every node now dead, loads zeroed
    addrs = await p.assign_batch(ids[10:])
    assert all(a in members for a in addrs)
    # Spread, not a single-node pileup: least-loaded round-robin.
    assert len(set(addrs)) == len(members)
    # Rebalance with the all-dead snapshot must not corrupt the directory
    # either (same funnel guard, every solver mode).
    await p.rebalance()
    for i in ids[10:]:
        assert await p.lookup(i) in members
    # Recovery: liveness returns -> the next rebalance re-seats cleanly.
    p.sync_members(members)
    await p.rebalance()
    for i in ids[10:]:
        assert await p.lookup(i) in members
    _ = p.count()


async def test_gossip_blip_marking_all_nodes_dead_spreads_and_stays_put():
    """The sync_members variant of the all-dead case (loads retained, no
    clean_server): the unguarded waterfill piled the whole batch onto ONE
    worst-scored node here — the condition-level guard must spread the
    batch least-loaded round-robin, and a rebalance under zero capacity
    must STAY PUT (reshuffling among dead nodes is pure churn) and say so
    in its stats mode."""
    p = JaxObjectPlacement(mode="sinkhorn", n_iters=8, move_cost=0.5)
    members = [f"10.9.1.{i}:70" for i in range(6)]
    p.sync_members(members)
    ids = [ObjectId("Blip", str(i)) for i in range(36)]
    await p.assign_batch(ids[:12])
    before = {str(i): await p.lookup(i) for i in ids[:12]}

    class _Dead:
        def __init__(self, a):
            self._a = a
            self.active = False
        def address(self):
            return self._a

    p.sync_members([_Dead(a) for a in members])  # every node inactive
    addrs = await p.assign_batch(ids[12:])
    assert all(a in members for a in addrs)
    assert len(set(addrs)) == len(members)  # spread, not a pileup
    moved = await p.rebalance()
    assert moved == 0
    assert p.stats.mode.endswith("+no_capacity")
    for i in ids[:12]:  # pre-blip seats untouched
        assert await p.lookup(i) == before[str(i)]
    # Liveness returns: the next rebalance runs the real solver again.
    p.sync_members(members)
    await p.rebalance()
    assert not p.stats.mode.endswith("+no_capacity")
    for i in ids:
        assert await p.lookup(i) in members


def test_least_loaded_spread_prefers_schedulable_prefix():
    """Overflow seats cycle ONLY schedulable (alive AND capacity>0) nodes
    while any exist (cordon's no-new-seats contract, and the operator's
    capacity=0 don't-place-here signal); dead nodes' zeroed loads must not
    rank them first."""
    from rio_tpu.object_placement.jax_placement import _least_loaded_spread

    load = np.array([5, 0, 3, 1], np.float32)  # node 1 dead, load zeroed
    alive = np.array([1, 0, 1, 1], np.float32)
    cap = np.ones(4, np.float32)
    out = _least_loaded_spread(load, alive, cap, 4, 7)
    assert 1 not in out.tolist()
    assert out[0] == 3  # least-loaded schedulable node first
    # A lone alive node with capacity=0 must NOT absorb the whole batch
    # while other schedulable nodes exist.
    cap0 = np.array([1, 1, 1, 0], np.float32)
    out = _least_loaded_spread(load, alive, cap0, 4, 7)
    assert 3 not in out.tolist() and 1 not in out.tolist()
    # All-dead: every real node cycles (any seat beats a pad index).
    out = _least_loaded_spread(load, np.zeros(4, np.float32), cap, 4, 8)
    assert sorted(set(out.tolist())) == [0, 1, 2, 3]
    # All-dead-or-capacity-zero: still spreads over every real node
    # rather than piling onto the lone alive capacity-zero node.
    alive_only3 = np.array([0, 0, 0, 1], np.float32)
    out = _least_loaded_spread(load, alive_only3, cap0, 4, 8)
    assert sorted(set(out.tolist())) == [0, 1, 2, 3]


async def test_hierarchical_solve_sanitizes_nonfinite_features(monkeypatch):
    """ISSUE 18 satellite: garbage feature rows (a NaN/inf-emitting custom
    hook) must not poison the solve. One NaN row would propagate through
    the coarse cost's std normalization into EVERY object's cost; the
    streamed obj_feat builder zeroes non-finite entries instead, so the
    directory stays complete, on live members, and balanced."""
    import numpy as np

    from rio_tpu.object_placement.jax_placement import _hash_features

    def poisoned(keys):
        feats = np.asarray(_hash_features(keys), np.float32).copy()
        for i, k in enumerate(keys):
            if k.endswith("3"):
                feats[i, 0] = np.nan
                feats[i, 1] = np.inf
            elif k.endswith("7"):
                feats[i] = -np.inf
        return feats

    p = JaxObjectPlacement(
        mode="hierarchical", n_iters=10, obj_features=poisoned
    )
    members = [f"10.33.0.{i}:70" for i in range(8)]
    p.sync_members(members)
    ids = [ObjectId("Nan", str(i)) for i in range(640)]
    await p.assign_batch(ids)
    await p.rebalance()
    addrs = [await p.lookup(i) for i in ids]
    assert all(a in members for a in addrs)
    from collections import Counter

    loads = Counter(addrs)
    assert max(loads.values()) <= 2.0 * (640 / 8)
    # The solve itself converged on finite numbers.
    assert np.isfinite(p.stats.residual) or p.stats.residual == -1.0


async def test_hierarchical_bf16_feature_knob(monkeypatch):
    """RIO_TPU_HIER_FEAT_BF16=1 stores the streamed feature block in
    bfloat16 (half the host bytes at 10M rows); the solve upcasts on
    device and the directory contract is unchanged."""
    monkeypatch.setenv("RIO_TPU_HIER_FEAT_BF16", "1")
    p = JaxObjectPlacement(mode="hierarchical", n_iters=10)
    members = [f"10.34.0.{i}:70" for i in range(8)]
    p.sync_members(members)
    ids = [ObjectId("Bf", str(i)) for i in range(640)]
    await p.assign_batch(ids)
    await p.rebalance()
    addrs = [await p.lookup(i) for i in ids]
    assert all(a in members for a in addrs)
    from collections import Counter

    loads = Counter(addrs)
    assert max(loads.values()) <= 2.0 * (640 / 8)


async def test_flat_rebalance_at_scale_composes_with_mesh(monkeypatch):
    """ISSUE 18 tentpole routing: the _FLAT_REBALANCE_MAX_ROWS guard used
    to refuse giant flat solves; on a mesh it now lands on the composed
    mesh x chunk dispatch (chunks AND devices both bound the compiled
    shape) and says so in SolveStats."""
    from rio_tpu.object_placement import jax_placement as jp_mod
    from rio_tpu.parallel import make_mesh

    monkeypatch.setattr(jp_mod, "_FLAT_REBALANCE_MAX_ROWS", 256)
    monkeypatch.setattr(jp_mod, "_HIER_CHUNK_ROWS", 64)
    p = JaxObjectPlacement(mode="sinkhorn", n_iters=10, mesh=make_mesh())
    members = [f"10.35.0.{i}:70" for i in range(6)]
    p.sync_members(members)
    ids = [ObjectId("BigMesh", str(i)) for i in range(3000)]
    await p.assign_batch(ids)
    moved = await p.rebalance()
    assert p.stats.mode == "sinkhorn+hier_at_scale+mesh_chunk"
    assert p.stats.devices == 8
    assert p.stats.chunks > 1
    assert moved >= 0
    addrs = [await p.lookup(i) for i in ids]
    assert all(a in members for a in addrs)
