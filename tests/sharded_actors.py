"""Shared actors for the sharded-server cross-process tests.

Imported by BOTH sides of the real-socket runs: each sharded worker
process builds its registry from ``tests.sharded_actors:build_registry``;
the parent test imports this module so the ``@message`` decorators
register the same wire names for the client's codec. Keep it
dependency-light — workers boot with a clean env.
"""

import asyncio

from rio_tpu import AppData, Registry, ServerInfo, ServiceObject, handler, message


@message(name="sh.Bump")
class Bump:
    amount: int = 1


@message(name="sh.Get")
class Get:
    pass


@message(name="sh.Val")
class Val:
    value: int = 0
    address: str = ""
    overlapped: int = 0


class ShardCounter(ServiceObject):
    """Volatile counter with a deliberate read-modify-write window.

    ``bump`` reads, yields the event loop, then writes — so two handlers
    interleaving on the SAME instance lose updates and flip ``overlapped``.
    Under the per-object serialized-execution invariant the final value
    must equal the number of bumps and ``overlapped`` must stay 0, even
    with the requests fanned across a sharded node's worker processes.
    """

    def __init__(self):
        self.value = 0
        self.overlapped = 0
        self._busy = False

    def __migrate_state__(self):
        return {"value": self.value}

    def __restore_state__(self, state):
        self.value = int(state["value"])

    @handler
    async def bump(self, msg: Bump, ctx: AppData) -> Val:
        if self._busy:
            self.overlapped += 1
        self._busy = True
        v = self.value
        await asyncio.sleep(0)  # open the interleave window
        self.value = v + msg.amount
        self._busy = False
        return Val(
            value=self.value,
            address=ctx.get(ServerInfo).address,
            overlapped=self.overlapped,
        )

    @handler
    async def get(self, msg: Get, ctx: AppData) -> Val:
        return Val(
            value=self.value,
            address=ctx.get(ServerInfo).address,
            overlapped=self.overlapped,
        )


def build_registry() -> Registry:
    return Registry().add_type(ShardCounter)
