"""Randomized invariants of the quota/rounding machinery.

Seeded property sweep over shapes, capacity skew, dead columns, and caller
drift — the hazards that produced real bugs in r3/r4 (global-gauge
underflow, fp32 quota drift at 2^24 buckets, refill-clip sentinel spill)
were all in this layer, found one at a time. Each case asserts the full
contract of ``exact_quota_repair`` (+ the spill guard), not one scenario.
"""

import jax.numpy as jnp
import numpy as np

from rio_tpu.ops.sinkhorn import exact_quota_repair, route_sentinel_spill


def _largest_remainder_quota(
    expected: np.ndarray, n: int, counts: np.ndarray
) -> np.ndarray:
    """Reference quota incl. the implementation's documented tie-break:
    remainder ties award the bonus to the MORE-OCCUPIED column (evicting a
    seated object to fill an empty tied column would be churn, not repair).
    """
    expected = np.maximum(expected.astype(np.float32), 0.0)  # impl dtype
    base = np.floor(expected).astype(np.int64)
    short = int(np.clip(n - base.sum(), 0, expected.shape[0]))
    rem = expected - base
    order = np.lexsort((-counts, -rem))
    quota = base.copy()
    quota[order[:short]] += 1
    return quota


def test_exact_quota_repair_randomized_contract():
    rng = np.random.RandomState(7)
    for case in range(40):
        m = int(rng.randint(3, 65))
        n = int(rng.randint(m, 40 * m))
        idx = rng.randint(0, m, size=n).astype(np.int32)
        # Expected marginals: random positive shares summing to ~n, with a
        # random subset of dead (zero-expected) columns.
        w = rng.gamma(0.7, 1.0, size=m) + 1e-3
        dead = rng.rand(m) < 0.2
        w[dead] = 0.0
        if not w.sum():
            w[0] = 1.0
            dead[0] = False
        expected = w / w.sum() * n
        out = np.asarray(
            exact_quota_repair(jnp.asarray(idx), jnp.asarray(expected))
        )
        # 1. In range.
        assert out.min() >= 0 and out.max() < m, case
        counts = np.bincount(out, minlength=m)
        # 2. Exact largest-remainder quotas on every column.
        initial = np.bincount(idx, minlength=m)
        quota = _largest_remainder_quota(expected, n, initial)
        assert counts.tolist() == quota.tolist(), (case, counts, quota)
        # 3. Dead columns end empty.
        assert counts[dead].sum() == 0, case
        # 4. Minimal moves: only the per-column overshoot is re-slotted.
        overshoot = np.maximum(initial - quota, 0).sum()
        moved = int((out != idx).sum())
        assert moved <= overshoot, (case, moved, overshoot)


def test_exact_quota_repair_prefer_keep_randomized():
    rng = np.random.RandomState(11)
    for case in range(20):
        m = int(rng.randint(3, 33))
        n = int(rng.randint(2 * m, 30 * m))
        idx = rng.randint(0, m, size=n).astype(np.int32)
        prefer = rng.rand(n) < 0.5
        expected = np.full(m, n / m, dtype=np.float64)
        out = np.asarray(
            exact_quota_repair(
                jnp.asarray(idx),
                jnp.asarray(expected),
                prefer_keep=jnp.asarray(prefer),
            )
        )
        quota = _largest_remainder_quota(expected, n, np.bincount(idx, minlength=m))
        counts = np.bincount(out, minlength=m)
        assert counts.tolist() == quota.tolist(), case
        # Eviction order: in every column, a preferred object may only be
        # evicted once NO non-preferred object kept its seat there (i.e.
        # preferred evictions imply the column's keepers are all preferred).
        for col in range(m):
            here = idx == col
            kept = here & (out == idx)
            evicted = here & (out != idx)
            if (evicted & prefer).any():
                assert not (kept & ~prefer).any(), (case, col)


def test_sentinel_spill_guard_randomized():
    rng = np.random.RandomState(13)
    for case in range(20):
        s = int(rng.randint(2, 17))
        n = int(rng.randint(4, 200))
        local = rng.randint(0, s + 1, size=n).astype(np.int32)
        mass = (rng.rand(n) < 0.8).astype(np.float32)
        cap = rng.gamma(1.0, 1.0, size=s).astype(np.float32)
        cap[rng.rand(s) < 0.3] = 0.0
        if not cap.sum():
            cap[0] = 1.0
        out = np.asarray(
            route_sentinel_spill(
                jnp.asarray(local), jnp.asarray(mass) > 0, s, jnp.asarray(cap)
            )
        )
        real = mass > 0
        # Real rows never sit on/after the sentinel; spilled ones landed on
        # the argmax-capacity column; everyone else is untouched.
        assert (out[real] < s).all(), case
        spilled = real & (local >= s)
        assert (out[spilled] == int(np.argmax(cap))).all(), case
        untouched = ~spilled
        assert (out[untouched] == local[untouched]).all(), case
