"""Randomized invariants of the quota/rounding machinery.

Seeded property sweep over shapes, capacity skew, dead columns, and caller
drift — the hazards that produced real bugs in r3/r4 (global-gauge
underflow, fp32 quota drift at 2^24 buckets, refill-clip sentinel spill)
were all in this layer, found one at a time. Each case asserts the full
contract of ``exact_quota_repair`` (+ the spill guard), not one scenario.
"""

import jax.numpy as jnp
import numpy as np

from rio_tpu.ops.sinkhorn import exact_quota_repair, route_sentinel_spill


def _largest_remainder_quota(
    expected: np.ndarray, n: int, counts: np.ndarray
) -> np.ndarray:
    """Reference quota incl. the implementation's documented tie-break:
    remainder ties award the bonus to the MORE-OCCUPIED column (evicting a
    seated object to fill an empty tied column would be churn, not repair).
    """
    expected = np.maximum(expected.astype(np.float32), 0.0)  # impl dtype
    base = np.floor(expected).astype(np.int64)
    short = int(np.clip(n - base.sum(), 0, expected.shape[0]))
    rem = expected - base
    order = np.lexsort((-counts, -rem))
    quota = base.copy()
    quota[order[:short]] += 1
    return quota


def test_exact_quota_repair_randomized_contract():
    rng = np.random.RandomState(7)
    for case in range(40):
        m = int(rng.randint(3, 65))
        n = int(rng.randint(m, 40 * m))
        idx = rng.randint(0, m, size=n).astype(np.int32)
        # Expected marginals: random positive shares summing to ~n, with a
        # random subset of dead (zero-expected) columns.
        w = rng.gamma(0.7, 1.0, size=m) + 1e-3
        dead = rng.rand(m) < 0.2
        w[dead] = 0.0
        if not w.sum():
            w[0] = 1.0
            dead[0] = False
        expected = w / w.sum() * n
        out = np.asarray(
            exact_quota_repair(jnp.asarray(idx), jnp.asarray(expected))
        )
        # 1. In range.
        assert out.min() >= 0 and out.max() < m, case
        counts = np.bincount(out, minlength=m)
        # 2. Exact largest-remainder quotas on every column.
        initial = np.bincount(idx, minlength=m)
        quota = _largest_remainder_quota(expected, n, initial)
        assert counts.tolist() == quota.tolist(), (case, counts, quota)
        # 3. Dead columns end empty.
        assert counts[dead].sum() == 0, case
        # 4. Minimal moves: only the per-column overshoot is re-slotted.
        overshoot = np.maximum(initial - quota, 0).sum()
        moved = int((out != idx).sum())
        assert moved <= overshoot, (case, moved, overshoot)


def test_exact_quota_repair_prefer_keep_randomized():
    rng = np.random.RandomState(11)
    for case in range(20):
        m = int(rng.randint(3, 33))
        n = int(rng.randint(2 * m, 30 * m))
        idx = rng.randint(0, m, size=n).astype(np.int32)
        prefer = rng.rand(n) < 0.5
        expected = np.full(m, n / m, dtype=np.float64)
        out = np.asarray(
            exact_quota_repair(
                jnp.asarray(idx),
                jnp.asarray(expected),
                prefer_keep=jnp.asarray(prefer),
            )
        )
        quota = _largest_remainder_quota(expected, n, np.bincount(idx, minlength=m))
        counts = np.bincount(out, minlength=m)
        assert counts.tolist() == quota.tolist(), case
        # Eviction order: in every column, a preferred object may only be
        # evicted once NO non-preferred object kept its seat there (i.e.
        # preferred evictions imply the column's keepers are all preferred).
        for col in range(m):
            here = idx == col
            kept = here & (out == idx)
            evicted = here & (out != idx)
            if (evicted & prefer).any():
                assert not (kept & ~prefer).any(), (case, col)


def test_sentinel_spill_guard_randomized():
    rng = np.random.RandomState(13)
    for case in range(20):
        s = int(rng.randint(2, 17))
        n = int(rng.randint(4, 200))
        local = rng.randint(0, s + 1, size=n).astype(np.int32)
        mass = (rng.rand(n) < 0.8).astype(np.float32)
        cap = rng.gamma(1.0, 1.0, size=s).astype(np.float32)
        cap[rng.rand(s) < 0.3] = 0.0
        if not cap.sum():
            cap[0] = 1.0
        out = np.asarray(
            route_sentinel_spill(
                jnp.asarray(local), jnp.asarray(mass) > 0, s, jnp.asarray(cap)
            )
        )
        real = mass > 0
        # Real rows never sit on/after the sentinel; spilled ones landed on
        # the argmax-capacity column; everyone else is untouched.
        assert (out[real] < s).all(), case
        spilled = real & (local >= s)
        assert (out[spilled] == int(np.argmax(cap))).all(), case
        untouched = ~spilled
        assert (out[untouched] == local[untouched]).all(), case


# ---------------------------------------------------------------------------
# K-seat anti-affinity standby placement (rio_tpu/replication)
# ---------------------------------------------------------------------------


def test_multi_seat_plan_randomized_anti_affinity_contract():
    """The replication acceptance bar: across random shapes, dead nodes,
    load skew, and K, a filled seat NEVER lands on the primary or on an
    earlier seat of the same object, never on a dead/zero-capacity node,
    and every seat that is feasible (enough live allowed nodes) is filled.
    """
    from rio_tpu.object_placement.jax_placement import multi_seat_plan

    rng = np.random.RandomState(11)
    for case in range(25):
        m = int(rng.randint(3, 11))
        n = int(rng.randint(5, 200))
        k = int(rng.randint(1, 4))
        alive = (rng.rand(m) > 0.25).astype(np.float32)
        if alive.sum() == 0:
            alive[rng.randint(m)] = 1.0
        cap = rng.uniform(0.5, 4.0, size=m).astype(np.float32)
        cap[rng.rand(m) < 0.15] = 0.0  # schedulable = alive AND cap > 0
        load = rng.uniform(0.0, 50.0, size=m).astype(np.float32)
        schedulable = (alive > 0) & (cap > 0)
        # Primaries seated anywhere, including (rarely) unseated rows (-1).
        primary = rng.randint(0, m, size=n).astype(np.int64)
        primary[rng.rand(n) < 0.05] = -1

        seats = multi_seat_plan(primary, k, load, cap, alive)
        assert seats.shape == (n, k)
        for i in range(n):
            filled = [int(s) for s in seats[i] if s >= 0]
            # Hard anti-affinity: no seat on the primary, seats distinct.
            assert primary[i] not in filled, (case, i)
            assert len(filled) == len(set(filled)), (case, i)
            # Seats only on schedulable nodes.
            for s in filled:
                assert schedulable[s], (case, i, s)
            # Feasibility: seat r is fillable iff the schedulable pool
            # minus the primary minus earlier seats is non-empty.
            pool = int(schedulable.sum()) - (
                1 if 0 <= primary[i] < m and schedulable[primary[i]] else 0
            )
            for r in range(k):
                if pool - r >= 1:
                    assert seats[i, r] >= 0, (case, i, r, pool)
                else:
                    assert seats[i, r] == -1, (case, i, r, pool)


def test_multi_seat_plan_degrades_not_violates():
    """Two schedulable nodes, every primary on node 0, k=2: seat 0 must be
    node 1 for every object and seat 1 must come back -1 — replication
    degrades rather than ever co-locating."""
    from rio_tpu.object_placement.jax_placement import multi_seat_plan

    n = 64
    seats = multi_seat_plan(
        np.zeros(n, np.int64),
        2,
        np.zeros(2, np.float32),
        np.ones(2, np.float32),
        np.ones(2, np.float32),
    )
    assert (seats[:, 0] == 1).all()
    assert (seats[:, 1] == -1).all()


def test_multi_seat_plan_balances_standby_load():
    """Uniform symmetric cluster: standby seats spread across nodes instead
    of piling onto one (the solver, not a fixed fallback, places them)."""
    from rio_tpu.object_placement.jax_placement import multi_seat_plan

    rng = np.random.RandomState(3)
    m, n = 8, 800
    primary = rng.randint(0, m, size=n).astype(np.int64)
    seats = multi_seat_plan(
        primary,
        1,
        np.zeros(m, np.float32),
        np.ones(m, np.float32),
        np.ones(m, np.float32),
    )
    assert (seats[:, 0] >= 0).all()
    counts = np.bincount(seats[:, 0], minlength=m)
    fair = n / m
    assert counts.max() <= 2.5 * fair, counts
    assert counts.min() >= fair / 4, counts


def test_multi_seat_plan_seats_track_capacity_marginal():
    """The capacity marginal — not the cost — governs aggregate seat counts:
    a node with 4x the capacity absorbs ~4x the standby seats. (Load enters
    the fill-ratio COST, which steers row->column matching; column totals
    are pinned by the Sinkhorn capacity marginal.)"""
    from rio_tpu.object_placement.jax_placement import multi_seat_plan

    rng = np.random.RandomState(5)
    m, n = 6, 600
    cap = np.ones(m, np.float32)
    cap[0] = 4.0
    primary = rng.randint(1, m, size=n).astype(np.int64)  # node 0 never primary
    seats = multi_seat_plan(
        primary, 1, np.zeros(m, np.float32), cap, np.ones(m, np.float32)
    )
    counts = np.bincount(seats[:, 0], minlength=m)
    expect0 = n * 4.0 / 9.0
    assert abs(counts[0] - expect0) <= 0.15 * expect0, counts
    small = counts[1:]
    assert abs(small.max() - small.min()) <= 0.3 * small.mean(), counts
