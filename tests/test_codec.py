"""Unit tests for the value codec + framing (rio_tpu.codec)."""

from dataclasses import dataclass, field
from enum import Enum, IntEnum
from typing import Any, Optional

import pytest

from rio_tpu import codec
from rio_tpu.errors import SerializationError


@dataclass
class Inner:
    x: int
    y: float


@dataclass
class Outer:
    name: str
    inner: Inner
    tags: list[str]
    blob: bytes
    maybe: Optional[int] = None
    table: dict[str, int] = field(default_factory=dict)


class Color(Enum):
    RED = "red"
    BLUE = "blue"


class Level(IntEnum):
    LOW = 1
    HIGH = 2


def test_primitive_roundtrip():
    for v in (1, -5, 0, 3.25, "hello", b"\x00\xff", True, False, None):
        assert codec.deserialize(codec.serialize(v), type(v) if v is not None else Any) == v


def test_dataclass_roundtrip():
    o = Outer("a", Inner(1, 2.5), ["t1", "t2"], b"xyz", maybe=7, table={"k": 1})
    assert codec.deserialize(codec.serialize(o), Outer) == o


def test_dataclass_is_positional_compact():
    # bincode-like: no field names on the wire
    data = codec.serialize(Inner(1, 2.0))
    assert b"x" not in data and b"y" not in data


def test_optional_none_roundtrip():
    o = Outer("a", Inner(0, 0.0), [], b"")
    assert codec.deserialize(codec.serialize(o), Outer).maybe is None


def test_enum_roundtrip():
    assert codec.deserialize(codec.serialize(Color.BLUE), Color) is Color.BLUE
    assert codec.deserialize(codec.serialize(Level.HIGH), Level) is Level.HIGH


def test_nested_containers():
    v = {"a": [Inner(1, 1.0), Inner(2, 2.0)]}
    out = codec.deserialize(codec.serialize(v), dict[str, list[Inner]])
    assert out == v


def test_tuple_and_set():
    assert codec.deserialize(codec.serialize((1, "a")), tuple[int, str]) == (1, "a")
    assert codec.deserialize(codec.serialize({3, 1, 2}), set[int]) == {1, 2, 3}


def test_schema_evolution_appended_field_tolerated():
    # Old reader (Inner) can decode wire written with extra trailing data? No:
    # extra fields are an error (strict, like bincode).
    data = codec.serialize([1, 2.0, "extra"])
    with pytest.raises(SerializationError):
        codec.deserialize(data, Inner)


def test_missing_trailing_optional_fields_defaulted():
    # New reader with appended default field decodes old wire.
    @dataclass
    class InnerV2:
        x: int
        y: float
        z: str = "default"

    data = codec.serialize(Inner(5, 6.0))
    v2 = codec.deserialize(data, InnerV2)
    assert (v2.x, v2.y, v2.z) == (5, 6.0, "default")


def test_unserializable_raises():
    class NotAMessage:
        pass

    with pytest.raises(SerializationError):
        codec.serialize(NotAMessage())


def test_type_mismatch_raises():
    with pytest.raises(SerializationError):
        codec.deserialize(codec.serialize("str"), int)


def test_frame_roundtrip():
    f = codec.frame(b"hello")
    assert f[:4] == (5).to_bytes(4, "big")
    r = codec.FrameReader()
    assert r.feed(f) == [b"hello"]


def test_frame_reader_partial_and_multiple():
    f1, f2 = codec.frame(b"aa"), codec.frame(b"bbb")
    stream = f1 + f2
    r = codec.FrameReader()
    out = []
    for i in range(0, len(stream), 3):  # drip-feed 3 bytes at a time
        out.extend(r.feed(stream[i : i + 3]))
    assert out == [b"aa", b"bbb"]


def test_json_roundtrip_dataclass():
    o = Outer("a", Inner(1, 2.5), ["t"], b"\x00\x01", maybe=3, table={"k": 9})
    assert codec.deserialize_json(codec.serialize_json(o), Outer) == o


def test_json_optional_dataclass_field():
    @dataclass
    class Txn:
        amount: int

    @dataclass
    class S:
        last: Optional[Txn] = None

    s = S(last=Txn(amount=5))
    out = codec.deserialize_json(codec.serialize_json(s), S)
    assert isinstance(out.last, Txn) and out.last.amount == 5
    assert codec.deserialize_json(codec.serialize_json(S()), S).last is None


def test_json_int_keyed_dict():
    @dataclass
    class S:
        counts: dict[int, int] = field(default_factory=dict)

    s = S(counts={1: 2, 30: 4})
    out = codec.deserialize_json(codec.serialize_json(s), S)
    assert out.counts == {1: 2, 30: 4}
    assert all(isinstance(k, int) for k in out.counts)


def test_json_enum_keyed_dict():
    @dataclass
    class S:
        by_color: dict[Color, int] = field(default_factory=dict)

    s = S(by_color={Color.RED: 1, Color.BLUE: 2})
    out = codec.deserialize_json(codec.serialize_json(s), S)
    assert out.by_color == {Color.RED: 1, Color.BLUE: 2}


def test_json_bytes_sentinel_not_hijacking_user_dicts():
    @dataclass
    class S:
        meta: dict[str, str] = field(default_factory=dict)

    s = S(meta={"__bytes__": "deadbeef"})
    out = codec.deserialize_json(codec.serialize_json(s), S)
    assert out.meta == {"__bytes__": "deadbeef"}  # stays a dict, not bytes


def test_json_frozenset_roundtrip():
    @dataclass
    class S:
        tags: frozenset[int] = frozenset()

    out = codec.deserialize_json(codec.serialize_json(S(tags=frozenset({1, 2}))), S)
    assert out.tags == frozenset({1, 2})


def test_json_unknown_field_rejected():
    with pytest.raises(SerializationError):
        codec.deserialize_json('{"x": 1, "y": 2.0, "zz": 1}', Inner)


def test_frame_too_large_rejected():
    with pytest.raises(SerializationError):
        codec.frame(b"x" * (codec.MAX_FRAME + 1))
    r = codec.FrameReader()
    with pytest.raises(SerializationError):
        r.feed((codec.MAX_FRAME + 1).to_bytes(4, "big"))


def test_json_heterogeneous_tuple_roundtrip():
    # Regression: deserialize_json decoded tuple[int, str] with int only.
    import dataclasses
    from rio_tpu.codec import deserialize_json, serialize_json

    @dataclasses.dataclass
    class S:
        pair: tuple[int, str] = (0, "")

    wire = serialize_json(S(pair=(1, "a")))
    out = deserialize_json(wire, S)
    assert out.pair == (1, "a")


def test_json_missing_required_field_raises_serialization_error():
    import dataclasses
    import pytest
    from rio_tpu.codec import deserialize_json
    from rio_tpu.errors import SerializationError

    @dataclasses.dataclass
    class S:
        a: int
        b: int  # newly required field absent from stored JSON

    with pytest.raises(SerializationError):
        deserialize_json('{"a": 1}', S)


def test_compiled_decoder_fills_trailing_plain_defaults():
    """The exec-compiled fast-path decoder — not just the generic walker —
    must accept a short wire whose absent trailing fields have plain
    defaults (the appended-field evolution rule, now on the hot path so a
    legacy-format peer doesn't tax every decode)."""
    import dataclasses

    @dataclasses.dataclass
    class Evolved:
        a: str
        b: int
        c: tuple[str, str, bool] | None = None
        d: float = 1.5

    dec = codec._dc_decoder(Evolved)
    assert dec is not None
    # Short wire (legacy arity) straight into the compiled decoder.
    assert dec(["x", 3]) == Evolved("x", 3, None, 1.5)
    assert dec(["x", 3, ["t", "s", True]]) == Evolved("x", 3, ("t", "s", True), 1.5)
    assert dec(["x", 3, None, 2.0]) == Evolved("x", 3, None, 2.0)
    # Below the required floor / above total → the generic walker's errors.
    with pytest.raises(SerializationError):
        dec(["x"])
    with pytest.raises(SerializationError):
        dec(["x", 3, None, 2.0, "extra"])
    # End-to-end through deserialize too.
    data = codec.serialize(["x", 3])
    assert codec.deserialize(data, Evolved) == Evolved("x", 3)


def test_compiled_decoder_factory_defaults_use_generic_fallback():
    """default_factory fields can't be inlined as shared constants; a short
    wire there must still decode correctly (via the generic walker) with a
    FRESH container per instance."""
    import dataclasses

    @dataclasses.dataclass
    class WithFactory:
        a: int
        items: list[int] = dataclasses.field(default_factory=list)

    out1 = codec.deserialize(codec.serialize([7]), WithFactory)
    out2 = codec.deserialize(codec.serialize([8]), WithFactory)
    assert out1 == WithFactory(7) and out2 == WithFactory(8)
    out1.items.append(1)
    assert out2.items == []  # no shared mutable default


def test_json_bytes_survive_inside_untyped_containers():
    """Regression: ``__bytes__`` sentinels nested inside a bare ``list``
    (or ``dict``/``Any``) field came back as marker dicts, not bytes —
    persisted saga step rows loaded through a JSON state provider then
    fed ``bytes({'__bytes__': ...})`` downstream. Untyped decode must
    restore the sentinel at ANY depth."""

    @dataclass
    class Rec:
        rows: list = field(default_factory=list)
        extra: dict = field(default_factory=dict)
        blob: Any = None

    rec = Rec(
        rows=[["Gate", "g1", b"\x91\xa4hold", ["deep", b"\x00\xff"]]],
        extra={"k": b"\x01\x02", "nest": {"x": b"\x03"}},
        blob=[{"b": b"\x04"}],
    )
    out = codec.deserialize_json(codec.serialize_json(rec), Rec)
    assert out.rows == rec.rows
    assert out.extra == rec.extra
    assert out.blob == rec.blob
    # A dict that merely CONTAINS a __bytes__ key alongside others is data,
    # not a sentinel.
    odd = Rec(extra={"m": {"__bytes__": "zz-not-hex"}})
    back = codec.deserialize_json(codec.serialize_json(odd), Rec)
    assert back.extra == {"m": {"__bytes__": "zz-not-hex"}}
