"""Seeded wire fuzz: a live server must survive arbitrary garbage bytes.

The transport probes that found real bugs in earlier rounds (garbage
frame kinds, oversized length headers, truncated msgpack) pinned as a
deterministic regression: batches of seeded-random malformed input are
thrown at a real server socket, and after every batch the server must
still answer a well-formed request on a FRESH connection. Mirrors the
reference's posture that a bad client must never take the node down
(the frame loop's error handling, ``rio-rs/src/service.rs:370-459``).
"""

from __future__ import annotations

import asyncio
import random
import struct

from tests.test_aio_transport import _boot, _frame

from rio_tpu.protocol import decode_response

_MAGIC_BAD = [
    b"",  # empty write then close
    b"\x00" * 4,  # zero-length frame header
    struct.pack(">I", 2**31) + b"\x02",  # absurd length prefix
    struct.pack(">I", 5) + b"\xff\xff\xff\xff\xff",  # unknown kind + junk
    struct.pack(">I", 1) + b"\x00",  # request kind, empty body
    struct.pack(">I", 3) + b"\x00\x91\xc0",  # truncated envelope msgpack
    b"GET / HTTP/1.1\r\nHost: x\r\n\r\n",  # wrong protocol entirely
]


def _random_garbage(rng: random.Random) -> bytes:
    n = rng.randrange(1, 64)
    body = bytes(rng.randrange(256) for _ in range(n))
    if rng.random() < 0.5:
        # Plausible header, garbage body — exercises the decode path, not
        # just the framer.
        return struct.pack(">I", len(body)) + body
    return body


async def _poke_garbage(host: str, port: int, payload: bytes) -> None:
    try:
        reader, writer = await asyncio.open_connection(host, port)
    except OSError:
        return
    try:
        writer.write(payload)
        await writer.drain()
        # Give the server a beat to react (error response or drop).
        try:
            await asyncio.wait_for(reader.read(64), 0.2)
        except asyncio.TimeoutError:
            pass
    except OSError:
        pass  # server dropped us mid-write: acceptable
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except OSError:
            pass


async def _valid_roundtrip(host: str, port: int, tag: int) -> None:
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(_frame("fuzz-canary", tag))
        await writer.drain()
        hdr = await asyncio.wait_for(reader.readexactly(4), 5)
        (ln,) = struct.unpack(">I", hdr)
        raw = await asyncio.wait_for(reader.readexactly(ln), 5)
        assert decode_response(raw) is not None
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except OSError:
            pass


async def _fuzz_against(host: str, port: int) -> None:
    rng = random.Random(0xF022)
    for batch in range(8):
        payloads = list(_MAGIC_BAD) + [_random_garbage(rng) for _ in range(25)]
        await asyncio.gather(*[_poke_garbage(host, port, p) for p in payloads])
        # The node must still serve well-formed traffic.
        await _valid_roundtrip(host, port, tag=batch)


def test_server_survives_garbage_frames():
    async def run():
        server, task, host, port = await _boot()
        try:
            await _fuzz_against(host, port)
        finally:
            task.cancel()
            await asyncio.gather(task, return_exceptions=True)

    asyncio.run(asyncio.wait_for(run(), 60))


def test_native_server_survives_garbage_frames():
    """Same batches against the C++ epoll engine's framer/decoder — the
    native data plane must match the asyncio transport's refuse-and-keep-
    serving posture byte for byte (CLAUDE.md wire invariant)."""
    from rio_tpu import native

    if native.get() is None:
        import pytest

        pytest.skip("native library unavailable")

    async def run():
        from rio_tpu import (
            LocalObjectPlacement,
            LocalStorage,
            Registry,
            Server,
        )
        from rio_tpu.cluster.membership_protocol import LocalClusterProvider

        from tests.test_aio_transport import SleepyActor

        members = LocalStorage()
        server = Server(
            address="127.0.0.1:0",
            registry=Registry().add_type(SleepyActor),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=LocalObjectPlacement(),
            transport="native",
        )
        await server.prepare()
        addr = await server.bind()
        task = asyncio.create_task(server.run())
        for _ in range(100):
            if await members.active_members():
                break
            await asyncio.sleep(0.02)
        host, _, port = addr.rpartition(":")
        try:
            await _fuzz_against(host, int(port))
        finally:
            task.cancel()
            await asyncio.gather(task, return_exceptions=True)

    asyncio.run(asyncio.wait_for(run(), 60))
