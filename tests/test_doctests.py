"""Doctest runner: docstring examples are a first-class test surface
(mirroring the reference's ``cargo test --doc`` in its justfile)."""

import doctest

import pytest

import rio_tpu.codec
import rio_tpu.utils.backoff
import rio_tpu.utils.lru

MODULES = [
    rio_tpu.codec,
    rio_tpu.utils.backoff,
    rio_tpu.utils.lru,
]


@pytest.mark.parametrize("module", MODULES, ids=lambda m: m.__name__)
def test_module_doctests(module):
    results = doctest.testmod(module, verbose=False)
    assert results.failed == 0, f"{module.__name__}: {results.failed} doctest failures"


def test_codec_has_doctests():
    # Guard against silently losing the examples (testmod passes trivially
    # on a module with zero doctests).
    results = doctest.testmod(rio_tpu.codec, verbose=False)
    assert results.attempted >= 4
