"""Elastic autoscaling (ISSUE 19): policy units, the controller state
machine against deterministic fakes, and the directory-reseat integration.

The deterministic tier drives :class:`AutoscaleRuntime.tick` directly with
a fake membership view and a fake provisioner — every hysteresis /
cooldown / pending-drain branch is exercised without timers or real
servers. The integration tier boots real servers through
``run_integration_test`` and kills the controller's owner to prove the
``rio.Autoscale`` seat reseats through the standard dead-owner branch.

The chaos tier (SIGKILL mid-drain under storage faults, all three fake
backends) lives in tests/test_autoscale_chaos.py.
"""

import asyncio
import time

from rio_tpu import AppData, Registry
from rio_tpu.autoscale import (
    AUTOSCALE_ID,
    AUTOSCALE_TYPE,
    AutoscaleConfig,
    AutoscaleRuntime,
    NodeProvisioner,
    ScalePolicy,
    ScaleSnapshot,
    ScaleStatus,
)
from rio_tpu.cluster.storage import Member
from rio_tpu.journal import HEALTH, SCALE, Journal
from rio_tpu.load import ClusterLoadView, LoadVector

from .server_utils import run_integration_test

# ---------------------------------------------------------------------------
# Deterministic fakes
# ---------------------------------------------------------------------------


class FakeMembers:
    """Membership view the tests script per tick: address → load fields.

    ``active_members`` stamps a fresh epoch on every read, so the derived
    :class:`ClusterLoadView` always sees the rows as live heartbeats.
    """

    def __init__(self) -> None:
        self.rows: dict[str, dict] = {}

    def set(self, address: str, **fields) -> None:
        self.rows[address] = fields

    def drop(self, address: str) -> None:
        self.rows.pop(address, None)

    async def active_members(self):
        return [
            Member.from_address(
                addr,
                active=True,
                load=LoadVector(epoch=time.time(), **fields).encode(),
            )
            for addr, fields in self.rows.items()
        ]


class FakeProvisioner(NodeProvisioner):
    """Records actuations; provisioned nodes appear in the fake membership."""

    def __init__(self, members: FakeMembers, managed=()) -> None:
        self.members = members
        self._managed = list(managed)
        self.provisions: list[str] = []
        self.retires: list[tuple[str, bool]] = []
        self.fail_provision = False
        self._n = 0

    async def provision(self) -> str:
        if self.fail_provision:
            raise RuntimeError("provisioning backend down")
        self._n += 1
        address = f"10.0.0.{self._n}:7000"
        self._managed.append(address)
        self.members.set(address, inflight=0.0)
        self.provisions.append(address)
        return address

    async def retire(self, address: str, *, force: bool = False) -> None:
        self.retires.append((address, force))
        if address in self._managed:
            self._managed.remove(address)
        self.members.drop(address)

    def managed(self):
        return list(self._managed)


SELF = "127.0.0.1:9000"


def make_runtime(
    members: FakeMembers,
    provisioner: FakeProvisioner,
    *,
    policy: ScalePolicy | None = None,
) -> AutoscaleRuntime:
    policy = policy or ScalePolicy(
        min_nodes=1,
        max_nodes=4,
        high_pressure=100.0,
        low_pressure=10.0,
        sustain=2,
        ema_alpha=1.0,  # raw signal: the tests script exact pressures
        inflight_weight=1.0,
        lag_weight=0.0,
        rate_weight=0.0,
        shed_weight=0.0,
        out_cooldown_s=5.0,
        in_cooldown_s=5.0,
        drain_timeout_s=60.0,
    )
    runtime = AutoscaleRuntime(
        address=SELF,
        members_storage=members,
        config=AutoscaleConfig(provisioner=provisioner, policy=policy),
        app_data=AppData(),
        journal=Journal(node=SELF),
    )
    # Units never exercise the wire drain; record the request instead of
    # opening a real client against the fake storage.
    runtime.drain_requests = []

    async def _fake_drain(victim: str) -> None:
        runtime.drain_requests.append(victim)
        runtime._journal("drain_requested", victim, ok=True, detail="fake")

    runtime._request_drain = _fake_drain
    return runtime


def scale_events(runtime: AutoscaleRuntime) -> list:
    return list(runtime.journal.events(kinds=[SCALE]))


# ---------------------------------------------------------------------------
# ScalePolicy units
# ---------------------------------------------------------------------------


def test_policy_pressure_blends_per_node_terms():
    policy = ScalePolicy(
        inflight_weight=2.0, lag_weight=3.0, rate_weight=0.5, shed_weight=10.0
    )
    agg = {
        "rio.cluster.nodes": 4.0,
        "rio.cluster.inflight_total": 40.0,  # 10/node
        "rio.cluster.loop_lag_mean_ms": 5.0,  # already a mean, not divided
        "rio.cluster.req_rate_total": 200.0,  # 50/node
    }
    got = policy.pressure_of(agg, shed_rate_per_node=3.0)
    assert got == (10.0 * 2.0 + 5.0 * 3.0 + 50.0 * 0.5 + 3.0 * 10.0)
    # An empty cluster never divides by zero.
    assert policy.pressure_of({}) == 0.0


def test_policy_rules_encode_sustain_as_trend_windows():
    policy = ScalePolicy(sustain=4)
    rules = {r.name: r for r in policy.rules()}
    assert set(rules) == {
        "scale_out_sustained",
        "scale_in_sustained",
        "pressure_rising",
        "pressure_falling",
    }
    out, under = rules["scale_out_sustained"], rules["scale_in_sustained"]
    assert out.gauge == "rio.autoscale.overload" and out.kind == "rising"
    assert under.gauge == "rio.autoscale.underload" and under.kind == "rising"
    assert out.windows == 4 and under.windows == 4
    assert rules["pressure_falling"].kind == "falling"


def test_policy_as_dict_carries_operator_knobs():
    d = ScalePolicy(out_cooldown_s=7.0, drain_timeout_s=33.0).as_dict()
    for key in (
        "min_nodes",
        "max_nodes",
        "high_pressure",
        "low_pressure",
        "sustain",
        "out_cooldown_s",
        "in_cooldown_s",
        "cooldown_max_s",
        "drain_timeout_s",
    ):
        assert key in d, key
    assert d["out_cooldown_s"] == 7.0 and d["drain_timeout_s"] == 33.0


# ---------------------------------------------------------------------------
# Controller state machine (deterministic ticks)
# ---------------------------------------------------------------------------


def test_scale_out_requires_sustained_overload():
    async def main():
        members = FakeMembers()
        provisioner = FakeProvisioner(members)
        runtime = make_runtime(members, provisioner)
        members.set(SELF, inflight=50.0)  # in-band baseline sample
        await runtime.tick()
        members.set(SELF, inflight=500.0)  # pressure 500 >> band high 100

        first = await runtime.tick()
        assert not first.acted and provisioner.provisions == []

        second = await runtime.tick()
        assert second.acted and second.action == "scale_out"
        assert len(provisioner.provisions) == 1
        assert runtime.scale_outs == 1

        # Causality: the sustain alarm is journaled as a HEALTH event and
        # the decision's SCALE event names that rule as its trigger.
        health = [
            e for e in runtime.journal.events(kinds=[HEALTH])
            if e.key == "scale_out_sustained"
        ]
        assert health, "sustain alarm must journal before the decision"
        outs = [e for e in scale_events(runtime) if e.attrs["action"] == "scale_out"]
        assert outs and outs[0].attrs["rule"] == "scale_out_sustained"
        assert outs[0].key == provisioner.provisions[0]

    asyncio.run(main())


def test_single_spike_never_resizes():
    async def main():
        members = FakeMembers()
        provisioner = FakeProvisioner(members)
        runtime = make_runtime(members, provisioner)

        members.set(SELF, inflight=500.0)  # one spiky sample...
        await runtime.tick()
        members.set(SELF, inflight=50.0)  # ...back inside the band
        for _ in range(6):
            ack = await runtime.tick()
            assert not ack.acted
        assert provisioner.provisions == [] and provisioner.retires == []
        assert scale_events(runtime) == []

    asyncio.run(main())


def test_scale_out_respects_max_nodes():
    async def main():
        members = FakeMembers()
        provisioner = FakeProvisioner(members)
        policy = ScalePolicy(
            min_nodes=1, max_nodes=1, high_pressure=100.0, low_pressure=10.0,
            sustain=2, ema_alpha=1.0, inflight_weight=1.0, lag_weight=0.0,
            shed_weight=0.0,
        )
        runtime = make_runtime(members, provisioner, policy=policy)
        members.set(SELF, inflight=500.0)
        for _ in range(5):
            ack = await runtime.tick()
            assert not ack.acted
        assert provisioner.provisions == []

    asyncio.run(main())


def test_scale_in_respects_min_nodes():
    async def main():
        members = FakeMembers()
        provisioner = FakeProvisioner(members, managed=["10.0.0.1:7000"])
        members.set("10.0.0.1:7000", inflight=0.0)
        policy = ScalePolicy(
            min_nodes=2, max_nodes=4, high_pressure=100.0, low_pressure=10.0,
            sustain=2, ema_alpha=1.0, inflight_weight=1.0, lag_weight=0.0,
            shed_weight=0.0,
        )
        runtime = make_runtime(members, provisioner, policy=policy)
        members.set(SELF, inflight=0.0)  # deeply underloaded, but 2 == min
        for _ in range(5):
            ack = await runtime.tick()
            assert not ack.acted
        assert provisioner.retires == []

    asyncio.run(main())


def test_cooldown_blocks_back_to_back_decisions():
    async def main():
        members = FakeMembers()
        provisioner = FakeProvisioner(members)
        runtime = make_runtime(members, provisioner)
        members.set(SELF, inflight=50.0)  # in-band baseline sample
        await runtime.tick()
        members.set(SELF, inflight=500.0)
        await runtime.tick()
        ack = await runtime.tick()
        assert ack.action == "scale_out"

        # Overload persists, but the decorrelated-jitter cooldown holds.
        for _ in range(4):
            ack = await runtime.tick()
            assert not ack.acted
            assert "cooldown" in ack.detail
        assert len(provisioner.provisions) == 1

        # Cooldown expiry re-opens the band; streaks were reset by the
        # decision, so it takes a fresh sustain run to act again.
        runtime._cooldown_until = 0.0
        acted = False
        for _ in range(4):
            ack = await runtime.tick()
            acted = acted or ack.acted
        assert acted and len(provisioner.provisions) == 2

    asyncio.run(main())


def test_scale_in_drains_then_retires_on_departure():
    async def main():
        members = FakeMembers()
        victim = "10.0.0.1:7000"
        provisioner = FakeProvisioner(members, managed=[victim])
        members.set(SELF, inflight=50.0)  # in-band baseline sample
        members.set(victim, inflight=50.0)
        runtime = make_runtime(members, provisioner)
        await runtime.tick()
        members.set(SELF, inflight=1.0)
        members.set(victim, inflight=1.0)

        await runtime.tick()
        ack = await runtime.tick()
        assert ack.acted and ack.action == "scale_in" and ack.detail == victim
        assert runtime.pending == victim
        assert runtime.drain_requests == [victim]

        # Still a member: the pending drain owns the controller.
        ack = await runtime.tick()
        assert not ack.acted and "draining" in ack.detail
        assert provisioner.retires == []

        # The victim leaves membership (drain completed) → retire, un-forced.
        members.drop(victim)
        ack = await runtime.tick()
        assert ack.acted and ack.action == "retired"
        assert provisioner.retires == [(victim, False)]
        assert runtime.scale_ins == 1 and runtime.pending == ""

        actions = [e.attrs["action"] for e in scale_events(runtime)]
        assert actions == ["scale_in", "drain_requested", "retired"]
        retired = scale_events(runtime)[-1]
        assert retired.attrs["forced"] is False
        assert retired.attrs["rule"] == "scale_in_sustained"

    asyncio.run(main())


def test_drain_deadline_forces_the_retire():
    async def main():
        members = FakeMembers()
        victim = "10.0.0.1:7000"
        provisioner = FakeProvisioner(members, managed=[victim])
        members.set(SELF, inflight=1.0)
        members.set(victim, inflight=1.0)
        policy = ScalePolicy(
            min_nodes=1, max_nodes=4, high_pressure=100.0, low_pressure=10.0,
            sustain=2, ema_alpha=1.0, inflight_weight=1.0, lag_weight=0.0,
            shed_weight=0.0, drain_timeout_s=0.0,
        )
        runtime = make_runtime(members, provisioner, policy=policy)
        members.set(SELF, inflight=50.0)  # in-band baseline sample
        members.set(victim, inflight=50.0)
        await runtime.tick()
        members.set(SELF, inflight=1.0)
        members.set(victim, inflight=1.0)
        await runtime.tick()
        ack = await runtime.tick()
        assert ack.action == "scale_in"

        # Victim never leaves membership; the zero deadline has already
        # passed by the next tick → forced retire.
        ack = await runtime.tick()
        assert ack.acted and ack.action == "retired"
        assert provisioner.retires == [(victim, True)]
        retired = [
            e for e in scale_events(runtime) if e.attrs["action"] == "retired"
        ][-1]
        assert retired.attrs["forced"] is True

    asyncio.run(main())


def test_pending_scale_in_suppresses_new_decisions():
    async def main():
        members = FakeMembers()
        victim = "10.0.0.1:7000"
        provisioner = FakeProvisioner(members, managed=[victim])
        members.set(SELF, inflight=1.0)
        members.set(victim, inflight=50.0)
        members.set(SELF, inflight=50.0)  # in-band baseline sample
        runtime = make_runtime(members, provisioner)
        await runtime.tick()
        members.set(SELF, inflight=1.0)
        members.set(victim, inflight=1.0)
        await runtime.tick()
        ack = await runtime.tick()
        assert ack.action == "scale_in"

        # Load whipsaws to overload mid-drain: the pending scale-in still
        # owns the controller — no overlapping scale-out.
        members.set(SELF, inflight=500.0)
        for _ in range(4):
            ack = await runtime.tick()
            assert not ack.acted and "draining" in ack.detail
        assert provisioner.provisions == []

    asyncio.run(main())


def test_victim_pick_is_managed_only_and_never_self():
    members = FakeMembers()
    provisioner = FakeProvisioner(members, managed=["10.0.0.9:7000"])
    runtime = make_runtime(members, provisioner)

    def view_of(rows: dict[str, float]) -> ClusterLoadView:
        ms = [
            Member.from_address(
                a, active=True,
                load=LoadVector(inflight=v, epoch=time.time()).encode(),
            )
            for a, v in rows.items()
        ]
        return ClusterLoadView.from_members(ms)

    # The unmanaged idle node is NOT eligible; the busier managed one is.
    rows = {SELF: 0.0, "10.0.0.9:7000": 30.0, "10.0.0.2:7000": 0.0}
    got = runtime._pick_victim(view_of(rows), set(rows))
    assert got == "10.0.0.9:7000"

    # With nothing managed, any peer qualifies — lowest load, never self.
    provisioner._managed = []
    got = runtime._pick_victim(view_of({SELF: 0.0, "10.0.0.2:7000": 5.0,
                                        "10.0.0.3:7000": 1.0}),
                               {SELF, "10.0.0.2:7000", "10.0.0.3:7000"})
    assert got == "10.0.0.3:7000"

    # A cluster of one (only self) has no eligible victim.
    assert runtime._pick_victim(view_of({SELF: 0.0}), {SELF}) is None


def test_scale_out_failure_journals_and_arms_cooldown():
    async def main():
        members = FakeMembers()
        provisioner = FakeProvisioner(members)
        provisioner.fail_provision = True
        runtime = make_runtime(members, provisioner)
        members.set(SELF, inflight=50.0)  # in-band baseline sample
        await runtime.tick()
        members.set(SELF, inflight=500.0)
        await runtime.tick()
        ack = await runtime.tick()
        assert ack.action == "scale_out" and not ack.acted
        assert runtime.scale_outs == 0
        failed = [
            e for e in scale_events(runtime)
            if e.attrs["action"] == "scale_out_failed"
        ]
        assert failed and "down" in failed[0].attrs["error"]
        # The failure armed the cooldown — no hot retry loop against a
        # dead provisioning backend.
        ack = await runtime.tick()
        assert "cooldown" in ack.detail

    asyncio.run(main())


def test_status_snapshot_shape_and_decision_rows():
    async def main():
        members = FakeMembers()
        provisioner = FakeProvisioner(members)
        runtime = make_runtime(members, provisioner)
        members.set(SELF, inflight=50.0)  # in-band baseline sample
        await runtime.tick()
        members.set(SELF, inflight=500.0)
        await runtime.tick()
        await runtime.tick()

        s = runtime.status(limit=8)
        for key in (
            "address", "pressure", "nodes", "over_streak", "under_streak",
            "cooldown_s", "pending", "scale_outs", "scale_ins", "ticks",
            "alerts", "policy", "decisions",
        ):
            assert key in s, key
        assert s["address"] == SELF and s["scale_outs"] == 1
        # Positional decision rows: [wall_ts, action, node, rule, pressure,
        # nodes, detail] — append-only, the admin CLI indexes them.
        row = s["decisions"][-1]
        assert len(row) == 7
        assert row[1] == "scale_out" and row[3] == "scale_out_sustained"
        assert row[2] == provisioner.provisions[0]
        assert isinstance(row[0], float) and row[0] > 0

    asyncio.run(main())


# ---------------------------------------------------------------------------
# Integration: the seat reseats when its owner dies
# ---------------------------------------------------------------------------


def test_controller_reseats_after_owner_death():
    """Kill whichever node the directory seated ``rio.Autoscale`` on; the
    survivor's next poke takes the standard dead-owner branch and the
    controller answers from its new host — the framework's own failover,
    no autoscale-specific reseat code."""
    from rio_tpu.utils.routing_live import build_echo_registry

    def build_registry() -> Registry:
        return build_echo_registry()

    async def body(cluster):
        client = cluster.client()
        try:
            snap = None
            deadline = asyncio.get_event_loop().time() + 15.0
            while asyncio.get_event_loop().time() < deadline:
                try:
                    snap = await client.send(
                        AUTOSCALE_TYPE, AUTOSCALE_ID,
                        ScaleStatus(limit=4), returns=ScaleSnapshot,
                    )
                    if snap.address and snap.ticks > 0:
                        break
                except Exception:
                    pass
                await asyncio.sleep(0.05)
            assert snap is not None and snap.address, "controller never seated"
            owner = snap.address

            victims = [s for s in cluster.servers if s.local_address == owner]
            assert victims, f"owner {owner} is not one of our servers"
            victim = victims[0]
            idx = cluster.servers.index(victim)
            # Abrupt owner death (no drain): cancel its serve task — run()'s
            # teardown marks the member inactive, like a crashed process.
            cluster.tasks[idx].cancel()

            deadline = asyncio.get_event_loop().time() + 20.0
            reseated = ""
            while asyncio.get_event_loop().time() < deadline:
                try:
                    snap = await client.send(
                        AUTOSCALE_TYPE, AUTOSCALE_ID,
                        ScaleStatus(limit=4), returns=ScaleSnapshot,
                    )
                    if snap.address and snap.address != owner:
                        reseated = snap.address
                        break
                except Exception:
                    pass
                await asyncio.sleep(0.1)
            assert reseated, "controller never reseated after owner death"
            assert reseated != owner
        finally:
            client.close()

    # Both nodes are autoscale-enabled with a pinned min==max policy: the
    # controller ticks (so the test can observe it) but never has a
    # decision to make — this test is about the SEAT, not the policy. The
    # trait base suffices as the provisioner: it never actuates.
    server_kwargs = {
        "load_interval": 0.1,
        "autoscale_config": AutoscaleConfig(
            provisioner=NodeProvisioner(),
            policy=ScalePolicy(min_nodes=2, max_nodes=2),
            interval=0.1,
        ),
    }

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=2,
            timeout=45.0,
            server_kwargs=server_kwargs,
        )
    )
