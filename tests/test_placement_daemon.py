"""Churn-driven placement: the Server-owned daemon re-solves with ZERO app code.

VERDICT r2 #3 / SURVEY §7.3: the reference recovers lazily inside the
request path (``rio-rs/src/service.rs:227-298``); rio-tpu additionally
re-seats displaced objects *proactively* — gossip marks a node dead, the
``PlacementDaemon`` feeds liveness to ``JaxObjectPlacement.sync_members``
and triggers a warm-started ``rebalance()``, and traffic finds every object
already re-placed.  The application never touches the solver.
"""

import asyncio

from rio_tpu import AppData, LocalObjectPlacement, LocalStorage, Registry, ServiceObject, handler, message
from rio_tpu.commands import AdminCommand, ServerInfo
from rio_tpu.object_placement.jax_placement import AffinityTracker, JaxObjectPlacement
from rio_tpu.placement_daemon import PlacementDaemon, PlacementDaemonConfig

from .server_utils import Cluster, run_integration_test

N_OBJECTS = 96


@message
class Poke:
    pass


@message
class Where:
    address: str = ""


class Pin(ServiceObject):
    @handler
    async def poke(self, msg: Poke, ctx: AppData) -> Where:
        return Where(address=ctx.get(ServerInfo).address)

def build_registry() -> Registry:
    return Registry().add_type(Pin)


def test_daemon_reseats_displaced_objects_without_app_solver_calls():
    """Kill a node; the daemon alone re-places its objects (≈ displaced share)."""
    placement = JaxObjectPlacement(mode="greedy", move_cost=0.5)

    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            # Allocate a population across 3 nodes.
            for i in range(N_OBJECTS):
                await client.send(Pin, f"o{i}", Poke(), returns=Where)
            assert placement.count() == N_OBJECTS

            placed_before = {
                f"o{i}": await cluster.allocation_address("Pin", f"o{i}")
                for i in range(N_OBJECTS)
            }
            victim = max(
                cluster.addresses, key=lambda a: sum(1 for v in placed_before.values() if v == a)
            )
            displaced = [k for k, v in placed_before.items() if v == victim]
            assert displaced, "victim hosted nothing; test setup broken"

            # Kill the victim node via its admin channel (deterministic —
            # a wire-level kill could be retried onto a survivor).
            victim_server = next(
                s for s in cluster.servers if s.local_address == victim
            )
            victim_server.admin_sender().send(AdminCommand.server_exit())

            # Wait for the DAEMON (not the test, not the app) to re-solve.
            daemons = [
                s.placement_daemon
                for s in cluster.servers
                if getattr(s, "placement_daemon", None) is not None
            ]
            assert daemons, "placement daemon was not started"
            deadline = asyncio.get_event_loop().time() + 15.0
            while asyncio.get_event_loop().time() < deadline:
                if any(d.stats.rebalances > 0 for d in daemons):
                    break
                await asyncio.sleep(0.05)
            else:
                raise TimeoutError("daemon never rebalanced after node death")

            # Every displaced object now has a LIVE owner in the directory —
            # proactively, before any traffic touched it.
            live = set(cluster.addresses) - {victim}
            reseated = 0
            deadline = asyncio.get_event_loop().time() + 10.0
            while asyncio.get_event_loop().time() < deadline:
                addrs = [
                    await cluster.allocation_address("Pin", k) for k in displaced
                ]
                reseated = sum(1 for a in addrs if a in live)
                if reseated == len(displaced):
                    break
                await asyncio.sleep(0.05)
            assert reseated == len(displaced), (
                f"{len(displaced) - reseated} displaced objects still "
                f"point at the dead node"
            )

            # Churn moved ≈ the displaced share, not a global reshuffle.
            moved_total = sum(d.stats.moves for d in daemons)
            assert moved_total >= len(displaced)
            assert moved_total <= len(displaced) + N_OBJECTS // 4, (
                f"daemon moved {moved_total} objects for {len(displaced)} displaced"
            )

            # And traffic is served from live nodes with no app solver call.
            for k in displaced[:8]:
                out = await client.send(Pin, k, Poke(), returns=Where)
                assert out.address in live
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=3,
            placement=placement,
            gossip=True,
            timeout=60.0,
            server_kwargs={
                "placement_daemon": True,
                "placement_daemon_config": PlacementDaemonConfig(
                    poll_interval=0.1, debounce=0.05, min_rebalance_interval=0.1
                ),
            },
        )
    )


def test_drain_flow_on_live_cluster():
    """The ops drain story end to end: cordon -> rebalance re-seats exactly
    the drained node's population -> traffic lands on survivors -> stop
    the server with nothing displaced (vs the reference's only exit:
    death + lazy re-allocation)."""
    placement = JaxObjectPlacement(mode="greedy", move_cost=0.5)

    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            for i in range(90):
                await client.send(Pin, f"o{i}", Poke(), returns=Where)

            async def seat(k):
                return await cluster.allocation_address("Pin", k)

            seats = {f"o{i}": await seat(f"o{i}") for i in range(90)}
            victim = max(
                cluster.addresses,
                key=lambda a: sum(1 for v in seats.values() if v == a),
            )
            on_victim = [k for k, v in seats.items() if v == victim]

            placement.cordon(victim)
            moved = await placement.rebalance()
            # ~Exactly the drained population moves (stay-put discount;
            # +small slack for integer-quota repair ties).
            assert on_victim and len(on_victim) <= moved <= len(on_victim) + 5, (
                moved, len(on_victim),
            )
            for k in on_victim:
                assert await seat(k) != victim
            for k in on_victim[:10]:
                out = await client.send(Pin, k, Poke(), returns=Where)
                assert out.address != victim

            # Stopping the drained server displaces nothing.
            next(
                s for s in cluster.servers if s.local_address == victim
            ).admin_sender().send(AdminCommand.server_exit())
            await asyncio.sleep(0.3)
            for k in on_victim[:10]:
                out = await client.send(Pin, k, Poke(), returns=Where)
                assert out.address != victim
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=3,
            placement=placement,
            timeout=60.0,
        )
    )


def test_admin_drain_command_full_flow():
    """AdminCommand.drain(): one admin message = cordon + re-solve +
    before_shutdown hooks for local instances + exit; re-seated rows are
    NEVER deleted (only rows still pointing at the draining node are)."""
    placement = JaxObjectPlacement(mode="greedy", move_cost=0.5)

    shutdowns: list[str] = []

    class DrainPin(ServiceObject):
        @handler
        async def poke(self, msg: Poke, ctx: AppData) -> Where:
            return Where(address=ctx.get(ServerInfo).address)

        async def before_shutdown(self, ctx: AppData) -> None:
            shutdowns.append(self.id)

    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            for i in range(60):
                await client.send(DrainPin, f"o{i}", Poke(), returns=Where)

            seats = {
                f"o{i}": await cluster.allocation_address("DrainPin", f"o{i}")
                for i in range(60)
            }
            victim = max(
                cluster.addresses,
                key=lambda a: sum(1 for v in seats.values() if v == a),
            )
            on_victim = [k for k, v in seats.items() if v == victim]
            victim_server = next(
                s for s in cluster.servers if s.local_address == victim
            )
            victim_server.admin_sender().send(AdminCommand.drain())

            # The server exits on its own once drained...
            deadline = asyncio.get_event_loop().time() + 15.0
            while asyncio.get_event_loop().time() < deadline:
                if victim_server._stopped.is_set():
                    break
                await asyncio.sleep(0.05)
            assert victim_server._stopped.is_set(), "drain never completed"
            # ...having run before_shutdown for ITS local instances...
            assert set(shutdowns) >= set(on_victim), (
                sorted(set(on_victim) - set(shutdowns))
            )
            # ...and the full population still resolves: re-seated rows
            # survived the lifecycle cleanup (nothing was over-deleted).
            for k, old in seats.items():
                addr = await cluster.allocation_address("DrainPin", k)
                assert addr is not None and addr != victim, (k, addr)
            for k in on_victim[:8]:
                out = await client.send(DrainPin, k, Poke(), returns=Where)
                assert out.address != victim
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=lambda: Registry().add_type(DrainPin),
            num_servers=3,
            placement=placement,
            timeout=60.0,
        )
    )


def test_draining_node_refuses_new_activations_but_serves_seated():
    """The quiesce gate behind drain: with the flag up, a node keeps
    serving objects already activated on it, but NEW objects bounce
    (deallocate -> client retry) and land on the other node."""
    placement = JaxObjectPlacement(mode="greedy")

    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            seeded = []
            for i in range(24):
                out = await client.send(Pin, f"s{i}", Poke(), returns=Where)
                seeded.append((f"s{i}", out.address))
            draining = cluster.servers[0]
            draining._draining.active = True
            # Seated objects on the draining node still serve...
            for k, addr in seeded:
                out = await client.send(Pin, k, Poke(), returns=Where)
                assert out.address == addr, (k, out.address, addr)
            # ...but every NEW object lands on the OTHER node.
            for i in range(24):
                out = await client.send(Pin, f"n{i}", Poke(), returns=Where)
                assert out.address != draining.local_address, f"n{i}"
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=2,
            placement=placement,
            timeout=60.0,
        )
    )


def test_daemon_noop_for_plain_providers():
    """Enabling the daemon with a CRUD-only provider must be harmless."""

    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            out = await client.send(Pin, "x", Poke(), returns=Where)
            assert out.address in cluster.addresses
            daemon = cluster.servers[0].placement_daemon
            assert daemon is not None and not daemon.supported
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=2,
            server_kwargs={"placement_daemon": True},
        )
    )


def test_dispatch_observe_feeds_affinity_tracker():
    """Served requests update the tracker with zero application wiring."""
    tracker = AffinityTracker()
    placement = JaxObjectPlacement(mode="hierarchical", affinity_tracker=tracker)

    async def body(cluster: Cluster):
        client = cluster.client()
        try:
            for i in range(8):
                await client.send(Pin, f"t{i}", Poke(), returns=Where)
            # The tracker saw every object, keyed exactly like the directory.
            assert len(tracker._obj) == 8
            for i in range(8):
                assert f"Pin.t{i}" in tracker._obj
        finally:
            client.close()

    asyncio.run(
        run_integration_test(
            body,
            registry_builder=build_registry,
            num_servers=2,
            placement=placement,
        )
    )


def test_shared_config_stats_isolated_per_daemon():
    """Servers sharing one config object must not share stats counters."""
    cfg = PlacementDaemonConfig()
    members, placement = LocalStorage(), LocalObjectPlacement()
    d1 = PlacementDaemon(members, placement, cfg)
    d2 = PlacementDaemon(members, placement, cfg)
    assert d1.stats is not d2.stats
    assert not d1.supported  # CRUD-only provider: daemon parks


def test_daemon_retries_after_epoch_discarded_solve():
    """A rebalance that loses the epoch race (stats.discarded) must be
    retried on the next poll — the churn event is still unserved — and a
    discarded attempt must never satisfy the sibling-skip epoch check."""
    from dataclasses import dataclass, field

    @dataclass
    class FakeStats:
        epoch: int = 0
        discarded: bool = False
        history: list = field(default_factory=list)

    class FlakyPlacement:
        """First rebalance is epoch-discarded; second commits."""

        def __init__(self):
            self.stats = FakeStats()
            self.rebalances = 0

        def sync_members(self, members):
            pass

        async def rebalance(self, *, mode=None):
            self.rebalances += 1
            prior = self.stats
            if self.rebalances == 1:
                archived = (
                    prior.history
                    + [FakeStats(epoch=prior.epoch, discarded=prior.discarded)]
                    if prior.epoch
                    else []
                )
                self.stats = FakeStats(
                    epoch=prior.epoch + 1, discarded=True, history=archived
                )
                return 0
            self.stats = FakeStats(epoch=prior.epoch + 1)
            return 7

    async def run():
        storage = LocalStorage()
        placement = FlakyPlacement()
        daemon = PlacementDaemon(
            storage,
            placement,
            PlacementDaemonConfig(
                poll_interval=0.05, debounce=0.01, min_rebalance_interval=0.0
            ),
        )
        from rio_tpu.cluster.storage import Member

        await storage.push(Member.from_address("10.3.0.1:90", active=True))
        await storage.push(Member.from_address("10.3.0.2:90", active=True))
        task = asyncio.create_task(daemon.run())
        try:
            await asyncio.sleep(0.2)  # first sync (no solve)
            # Churn: one node dies.
            await storage.set_inactive("10.3.0.2", 90)
            for _ in range(100):
                if daemon.stats.rebalances >= 1:
                    break
                await asyncio.sleep(0.05)
        finally:
            task.cancel()
            await asyncio.gather(task, return_exceptions=True)
        # The discarded attempt was recorded AND retried to completion.
        assert daemon.stats.rebalances_discarded == 1
        assert daemon.stats.rebalances == 1
        assert daemon.stats.moves == 7
        assert placement.rebalances == 2
        # One churn event, even though it took two attempts.
        assert daemon.stats.liveness_changes == 1

    asyncio.run(asyncio.wait_for(run(), 30))


def test_daemon_abandons_retries_after_consecutive_discards():
    """Sustained epoch races must not livelock the device: after
    max_discard_retries consecutive discards the daemon stops dispatching
    solves until the NEXT liveness change, which re-arms it."""
    from dataclasses import dataclass, field

    @dataclass
    class FakeStats:
        epoch: int = 0
        discarded: bool = False
        history: list = field(default_factory=list)

    class AlwaysDiscarded:
        """Every rebalance loses the epoch race (e.g. allocation traffic)."""

        def __init__(self):
            self.stats = FakeStats()
            self.rebalances = 0

        def sync_members(self, members):
            pass

        async def rebalance(self, *, mode=None):
            self.rebalances += 1
            self.stats = FakeStats(epoch=self.stats.epoch + 1, discarded=True)
            return 0

    async def run():
        storage = LocalStorage()
        placement = AlwaysDiscarded()
        daemon = PlacementDaemon(
            storage,
            placement,
            PlacementDaemonConfig(
                poll_interval=0.02,
                debounce=0.01,
                min_rebalance_interval=0.0,  # zero backoff: tests the CAP
                max_discard_retries=2,
            ),
        )
        from rio_tpu.cluster.storage import Member

        await storage.push(Member.from_address("10.4.0.1:90", active=True))
        await storage.push(Member.from_address("10.4.0.2:90", active=True))
        await storage.push(Member.from_address("10.4.0.3:90", active=True))
        task = asyncio.create_task(daemon.run())
        try:
            await asyncio.sleep(0.2)  # first sync (no solve)
            await storage.set_inactive("10.4.0.2", 90)
            for _ in range(100):
                if daemon.stats.retries_abandoned >= 1:
                    break
                await asyncio.sleep(0.05)
            assert daemon.stats.retries_abandoned == 1
            # Initial attempt + exactly max_discard_retries retries, then
            # silence: no further solves while liveness is stable.
            assert placement.rebalances == 3
            await asyncio.sleep(0.3)
            assert placement.rebalances == 3, "daemon kept solving after giving up"
            # A NEW churn event re-arms the daemon (and resets the ladder).
            await storage.set_inactive("10.4.0.3", 90)
            for _ in range(100):
                if placement.rebalances > 3:
                    break
                await asyncio.sleep(0.05)
            assert placement.rebalances > 3, "new churn did not re-arm the daemon"
        finally:
            task.cancel()
            await asyncio.gather(task, return_exceptions=True)
        assert daemon.stats.rebalances == 0  # every attempt was discarded
        assert daemon.stats.rebalances_discarded >= 3

    asyncio.run(asyncio.wait_for(run(), 30))


def test_event_kick_wakes_loop_before_poll_interval():
    """A provider churn event (cordon here; gossip liveness flips and
    clean_server fire the same listener) must wake the daemon NOW — with a
    deliberately enormous poll_interval the re-solve can only have come
    from the kick, and with a committed plan it lands as a delta."""

    async def run():
        from rio_tpu import ObjectId
        from rio_tpu.cluster.storage import Member

        addrs = [f"10.5.0.{i}:90" for i in range(4)]
        storage = LocalStorage()
        for a in addrs:
            await storage.push(Member.from_address(a, active=True))
        placement = JaxObjectPlacement(mode="greedy", node_axis_size=4)
        placement.sync_members(await storage.members())
        await placement.assign_batch([ObjectId("K", str(i)) for i in range(64)])
        await placement.rebalance(delta=False)  # commit the PlanState
        daemon = PlacementDaemon(
            storage,
            placement,
            PlacementDaemonConfig(
                poll_interval=60.0,  # the kick, not the poll, must wake us
                debounce=0.01,
                min_rebalance_interval=0.0,
            ),
        )
        task = asyncio.create_task(daemon.run())
        try:
            for _ in range(200):
                if daemon.stats.polls >= 1:
                    break
                await asyncio.sleep(0.01)
            assert daemon.stats.polls >= 1
            # Churn: storage learns the death; the provider-side cordon
            # fires the churn listener that wakes the sleeping loop.
            await storage.set_inactive("10.5.0.0", 90)
            placement.cordon(addrs[0])
            for _ in range(200):
                if daemon.stats.rebalances >= 1:
                    break
                await asyncio.sleep(0.05)
            assert daemon.stats.rebalances >= 1, "kick did not wake the loop"
            assert daemon.stats.kicks >= 1
            assert daemon.stats.delta_rebalances >= 1
            assert placement.stats.mode == "greedy+delta"
            # The displaced objects were re-seated off the dead node.
            dead_idx = placement._nodes[addrs[0]].index
            assert len(placement._by_node.get(dead_idx, ())) == 0
        finally:
            task.cancel()
            await asyncio.gather(task, return_exceptions=True)

    asyncio.run(asyncio.wait_for(run(), 30))


def test_event_kick_opt_out_leaves_listener_unregistered():
    async def run():
        storage = LocalStorage()
        from rio_tpu.cluster.storage import Member

        await storage.push(Member.from_address("10.6.0.1:90", active=True))
        await storage.push(Member.from_address("10.6.0.2:90", active=True))
        placement = JaxObjectPlacement(mode="greedy", node_axis_size=4)
        daemon = PlacementDaemon(
            storage,
            placement,
            PlacementDaemonConfig(poll_interval=60.0, event_kick=False),
        )
        task = asyncio.create_task(daemon.run())
        try:
            for _ in range(200):
                if daemon.stats.polls >= 1:
                    break
                await asyncio.sleep(0.01)
            assert placement._churn_listeners == []
            placement.cordon("10.6.0.1:90")
            await asyncio.sleep(0.05)
            assert daemon.stats.kicks == 0
        finally:
            task.cancel()
            await asyncio.gather(task, return_exceptions=True)

    asyncio.run(asyncio.wait_for(run(), 30))
