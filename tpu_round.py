"""Wedge-aware TPU round orchestrator (VERDICT r4 item 8).

Encodes the round's TPU schedule as a priority-ordered pipeline so a
mid-round relay wedge costs only the stages not yet run — never the
high-priority evidence:

  probe -> bench (chained headline -> BENCH_DETAIL.tpu.json)
        -> probe -> pallas slope head-to-head (PALLAS_TPU.json verdict)
        -> probe -> hier ladder (row 5, banked rung by rung)

Every TPU touch happens in a CHILD process with its own os._exit
watchdog (bench.py / tpu_pallas_check.py / tpu_probe.py already armor
themselves); this orchestrator never imports jax. Between stages it
re-probes and compares latency health against the FIRST green probe:
the relay degrades before it dies (r4: compile 66->106 s, pull 349->747
ms preceded the wedge), so rising numbers mean "stop launching now" and
the orchestrator halts with whatever is already banked.

Usage:
  python tpu_round.py             # one probe; run stages if green
  python tpu_round.py --wait      # probe every 15 min until green (<= 11 h)
  python tpu_round.py --status    # print the status file and exit

Status (machine-readable, updated after every transition):
  TPU_ROUND_STATUS.json — {phase, probes, stages: {name: rc/summary}, halted_reason}
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
STATUS_PATH = os.path.join(HERE, "TPU_ROUND_STATUS.json")
PROBE_DEADLINE_S = 150.0
WAIT_INTERVAL_S = 15 * 60
# Absolute health ceilings (r4 data: healthy pull4mb ~350 ms, wedge-preceding
# ~750 ms) and relative degradation vs the first green probe of this run.
PULL4MB_MAX_MS = 1200.0
ROUNDTRIP_MAX_MS = 1500.0
DEGRADE_FACTOR = 2.5


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


class Status:
    def __init__(self) -> None:
        self.data = {
            "started": _now(),
            "phase": "init",
            "probes": [],
            "stages": {},
            "halted_reason": None,
        }
        self.save()

    def save(self) -> None:
        self.data["updated"] = _now()
        tmp = STATUS_PATH + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self.data, fh, indent=1)
        os.replace(tmp, STATUS_PATH)

    def set(self, **kw) -> None:
        self.data.update(kw)
        self.save()


def probe(status: Status) -> dict:
    """One child probe; returns {rc, init_s?, roundtrip_ms?, pull4mb_ms?}."""
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(HERE, "tpu_probe.py"), str(PROBE_DEADLINE_S)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            cwd=HERE,
            timeout=PROBE_DEADLINE_S + 30,
        )
        rc, out = proc.returncode, proc.stdout.decode(errors="replace")
    except subprocess.TimeoutExpired:
        rc, out = 99, "(parent backstop timeout)"
    rec: dict = {"t": _now(), "rc": rc, "wall_s": round(time.monotonic() - t0, 1)}
    m = re.search(r"init_s=([\d.]+)", out)
    if m:
        rec["init_s"] = float(m.group(1))
    m = re.search(r"roundtrip_ms=([\d.]+) pull4mb_ms=([\d.]+)", out)
    if m:
        rec["roundtrip_ms"] = float(m.group(1))
        rec["pull4mb_ms"] = float(m.group(2))
    status.data["probes"].append(rec)
    status.save()
    print(f"# probe: {rec}", file=sys.stderr, flush=True)
    return rec


def health_ok(rec: dict, baseline: dict | None) -> str | None:
    """None when healthy, else a halt reason string."""
    if rec["rc"] != 0:
        return f"probe rc={rec['rc']}"
    rt, pull = rec.get("roundtrip_ms"), rec.get("pull4mb_ms")
    if rt is None or pull is None:
        return "probe green but no latency line"
    if pull > PULL4MB_MAX_MS or rt > ROUNDTRIP_MAX_MS:
        return f"latency over ceiling (roundtrip {rt} ms, pull4mb {pull} ms)"
    if baseline is not None:
        base_rt = baseline.get("roundtrip_ms")
        base_pull = baseline.get("pull4mb_ms")
        if base_pull and pull > DEGRADE_FACTOR * base_pull:
            return f"pull degraded {base_pull} -> {pull} ms"
        if base_rt and rt > DEGRADE_FACTOR * base_rt:
            return f"roundtrip degraded {base_rt} -> {rt} ms"
    return None


def run_stage(
    status: Status, name: str, cmd: list[str], budget_s: float,
    env_extra: dict | None = None,
) -> int:
    """Run one stage child, teeing output to TPU_ROUND_<name>.log."""
    log_path = os.path.join(HERE, f"TPU_ROUND_{name}.log")
    status.set(phase=f"stage:{name}")
    t0 = time.monotonic()
    env = None
    if env_extra:
        env = dict(os.environ)
        env.update(env_extra)
    with open(log_path, "w") as log:
        try:
            proc = subprocess.run(
                cmd, stdout=log, stderr=subprocess.STDOUT, cwd=HERE,
                timeout=budget_s, env=env,
            )
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            # The stage children arm their own watchdogs well inside this
            # backstop; hitting it means a child wedged mid-op — do not
            # start anything else.
            rc = -1
    status.data["stages"][name] = {
        "rc": rc,
        "wall_s": round(time.monotonic() - t0, 1),
        "log": os.path.basename(log_path),
    }
    status.save()
    print(f"# stage {name}: rc={rc}", file=sys.stderr, flush=True)
    return rc


STAGES = [
    # (name, cmd, budget_s, env_extra) in strict priority order. bench.py
    # is FIRST: its collapsed chained tier is the round's #1 deliverable
    # and it banks BENCH_DETAIL.tpu.json clobber-proof. Budgets are parent
    # backstops sized ~1.3x the children's own summed watchdog deadlines.
    ("bench", [sys.executable, "bench.py"], 3600.0, None),
    (
        "pallas",
        [sys.executable, "tpu_pallas_check.py", "--deadline", "600"],
        1500.0,
        None,
    ),
    (
        "hier_ladder",
        [
            sys.executable, "bench.py", "--tier", "10485760", "--hier",
            "--deadline", "600",
        ],
        800.0,
        None,
    ),
    # The block-rows layout experiment (VERDICT r4 #3 / weak #6): one
    # kernel, larger grid blocks, banked under its own _br1024 key — runs
    # LAST because it is exploratory, not evidence the round depends on.
    (
        "pallas_br",
        [
            sys.executable, "tpu_pallas_check.py", "--deadline", "600",
            "--only", "pallas_scaling",
        ],
        800.0,
        {"RIO_TPU_PALLAS_BLOCK_ROWS": "1024"},
    ),
]


def run_round(status: Status, wait: bool, max_wait_s: float) -> int:
    waited = 0.0
    status.set(phase="probing")
    baseline = None
    while True:
        rec = probe(status)
        reason = health_ok(rec, None)
        if reason is None:
            baseline = rec
            break
        if not wait or waited >= max_wait_s:
            status.set(phase="no_window", halted_reason=reason)
            print(f"# no healthy window: {reason}", file=sys.stderr)
            return 2
        status.set(phase=f"waiting ({reason})")
        time.sleep(WAIT_INTERVAL_S)
        waited += WAIT_INTERVAL_S

    for i, (name, cmd, budget, env_extra) in enumerate(STAGES):
        rc = run_stage(status, name, cmd, budget, env_extra)
        if rc == -1:
            status.set(phase="halted", halted_reason=f"stage {name} hit parent backstop")
            return 3
        if i == len(STAGES) - 1:
            # No stage left to gate: a degraded post-run probe must not
            # flip a fully banked round to "halted" (the signal means
            # "don't launch MORE work", and there is none). Record health
            # for the next orchestrator run, but finish as done.
            rec = probe(status)
            note = health_ok(rec, baseline)
            status.set(
                phase="done",
                halted_reason=None,
                final_probe_note=note,
            )
            if note is not None:
                print(f"# done; post-run health note: {note}", file=sys.stderr)
            return 0
        rec = probe(status)
        reason = health_ok(rec, baseline)
        if reason is not None:
            status.set(phase="halted", halted_reason=f"after {name}: {reason}")
            print(f"# halting after {name}: {reason}", file=sys.stderr)
            return 3
    status.set(phase="done", halted_reason=None)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--wait", action="store_true")
    ap.add_argument("--max-wait-hours", type=float, default=11.0)
    ap.add_argument("--status", action="store_true")
    args = ap.parse_args()
    if args.status:
        try:
            with open(STATUS_PATH) as fh:
                print(fh.read())
        except OSError:
            print("{}")
        return 0
    status = Status()
    return run_round(status, args.wait, args.max_wait_hours * 3600.0)


if __name__ == "__main__":
    raise SystemExit(main())
