"""Repo-level pytest config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharded-solver tests run on
``xla_force_host_platform_device_count=8`` CPU devices instead (the same
mechanism the driver's ``dryrun_multichip`` uses). Must run before the first
``import jax`` anywhere in the test session.
"""

import asyncio
import inspect
import os

# Hard override: the ambient environment (sitecustomize) may pin
# JAX_PLATFORMS to the real TPU tunnel ("axon"); tests always run on the
# virtual CPU mesh, so force both the env var and the live jax config.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (must come after the env setup above)

jax.config.update("jax_platforms", "cpu")

# Defensive: deregister the axon TPU-tunnel PJRT plugin entirely. Even with
# jax_platforms=cpu its factory can be initialized during backend discovery,
# and a wedged tunnel (e.g. a stale chip grant) then hangs the whole test
# session on the first jax op.
try:  # pragma: no cover - environment-specific
    from jax._src import xla_bridge as _xb

    for _reg in ("_backend_factories", "backend_factories"):
        _factories = getattr(_xb, _reg, None)
        if isinstance(_factories, dict):
            _factories.pop("axon", None)
except Exception:
    pass


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: run async test on a fresh event loop")


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests on a fresh event loop (no pytest-asyncio dep)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
            if name in pyfuncitem.funcargs
        }
        asyncio.run(fn(**kwargs))
        return True
    return None
