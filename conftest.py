"""Repo-level pytest config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharded-solver tests run on
``xla_force_host_platform_device_count=8`` CPU devices instead (the same
mechanism the driver's ``dryrun_multichip`` uses). Must run before the first
``import jax`` anywhere in the test session.
"""

import asyncio
import inspect
import sys

sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent))

# Hard override: the ambient environment (sitecustomize) may pin
# JAX_PLATFORMS to the real TPU tunnel ("axon"); tests always run on the
# virtual 8-device CPU mesh. One shared implementation of the cpu pin +
# axon-factory deregistration (a wedged tunnel otherwise hangs the whole
# session on the first jax op) lives in rio_tpu.utils.jaxenv.
from rio_tpu.utils.jaxenv import force_cpu  # noqa: E402

force_cpu(n_devices=8)


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: run async test on a fresh event loop")
    config.addinivalue_line(
        "markers",
        "slow: long-running test (1M-actor stress, soak, multihost); "
        "tier-1 verify runs -m 'not slow'",
    )


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests on a fresh event loop (no pytest-asyncio dep)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
            if name in pyfuncitem.funcargs
        }
        asyncio.run(fn(**kwargs))
        return True
    return None
